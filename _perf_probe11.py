"""Donated-state variants: can donation unlock batch 12/16 or 6 layers?"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ray_tpu.models.llama import LlamaConfig, flops_per_token, init_params, loss_fn
from ray_tpu.parallel import (
    create_train_state, llama_param_shardings, make_mesh, shard_params,
)
from ray_tpu.parallel.train_step import TrainState

PEAK = 197e12
S = 1024
K = 2


def run(tag, batch, remat, layers=4, dim=4096, heads=32, kv=8, hidden=11008,
        timed=4):
    config = LlamaConfig(
        vocab_size=32000, dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=kv, hidden_dim=hidden, max_seq_len=S,
        attn_impl="flash", remat=remat, param_dtype=jnp.bfloat16)
    mesh = make_mesh({"data": -1})
    opt = optax.adamw(1e-4)
    state = create_train_state(
        shard_params(init_params(config, jax.random.key(0)),
                     llama_param_shardings(config, mesh)), opt)

    def one(st, toks):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, {"tokens": toks}, config))(st.params)
        updates, new_opt = opt.update(grads, st.opt_state, st.params)
        return TrainState(optax.apply_updates(st.params, updates), new_opt,
                          st.step + 1), loss

    @jax.jit
    def multi(st, toks_k):
        return lax.scan(one, st, toks_k)

    multi = jax.jit(lambda st, toks_k: lax.scan(one, st, toks_k),
                    donate_argnums=(0,))

    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32000, (K, batch, S)).astype("int32"))
    for _ in range(2):
        state, losses = multi(state, toks)
        float(losses[-1])
    times = []
    for _ in range(timed):
        t0 = time.perf_counter()
        state, losses = multi(state, toks)
        float(losses[-1])
        times.append((time.perf_counter() - t0) / K)
    per_step = min(times)
    toks_s = batch * (S - 1) / per_step
    mfu = toks_s * flops_per_token(config, S) / PEAK
    print(f"{tag:26s} step={per_step*1000:7.1f}ms "
          f"tok/s={toks_s:9.0f} mfu={mfu:.3f}", flush=True)


which = sys.argv[1]
if which == "b12r":
    run("1B b12 remat don", 12, True)
elif which == "b16r":
    run("1B b16 remat don", 16, True)
elif which == "l6b8":
    run("1.4B L6 b8 remat don", 8, True, layers=6)
elif which == "l8b8":
    run("1.8B L8 b8 remat don", 8, True, layers=8)
elif which == "b24r":
    run("1B b24 remat don", 24, True)
