// Shared-memory arena object store — the native hot path of the per-node
// store (role-equivalent to plasma's mmap'd arenas + dlmalloc:
// `src/ray/object_manager/plasma/store.cc:1`, `plasma_allocator.h`).
//
// One mmap'd tmpfs file per node holds every object; allocation is a
// first-fit free list with coalescing; metadata (id -> extent, seal/pin
// bits, LRU stamps) lives in the owning raylet process. Clients receive
// (arena path, offset, size) and map the arena once — create/get never
// touch a per-object file, so small-object churn costs an allocator walk
// instead of three syscalls.
//
// Exposed as a C ABI consumed through ctypes (the image has no pybind11).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;
constexpr uint64_t kInvalid = ~0ull;

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool pinned = false;
  uint32_t refs = 0;  // client mappings (plasma-style: space with live
                      // readers is never reused by evict/spill)
  uint64_t lru = 0;   // monotonic access stamp
};

struct Store {
  // Guards entries/free_list/counters. Callers are nominally the
  // raylet's single event loop, but ctypes releases the GIL around C
  // calls, so any second Python thread would otherwise race.
  std::mutex mu;
  int fd = -1;
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t lru_clock = 0;
  uint64_t num_evictions = 0;
  std::unordered_map<std::string, Entry> entries;
  // free extents keyed by offset -> size (coalescing on release)
  std::map<uint64_t, uint64_t> free_list;

  // One background sweep that commits every arena page at open.
  std::thread toucher;
  std::atomic<bool> closing{false};
  // Highest byte ever allocated; the toucher pre-commits a bounded
  // window ahead of it instead of the whole declared capacity, so a
  // mostly-empty store does not become RAM-resident up front (full
  // residency can OOM memory-tight hosts that lazy faulting spared).
  std::atomic<uint64_t> watermark{0};

  void toucher_main() {
    // RTPU_ARENA_PRECOMMIT: "ahead" (default) commits up to 256MB past
    // the allocation watermark; "full" commits the whole capacity up
    // front (dedicated hosts where the budget is truly reserved);
    // "off" leaves every fault to first touch.
    const char* mode_env = ::getenv("RTPU_ARENA_PRECOMMIT");
    std::string mode = mode_env ? mode_env : "ahead";
    if (mode == "off") return;
    const uint64_t headroom = 256ull << 20;
    uint64_t pos = 0;
    while (pos < capacity && !closing.load(std::memory_order_relaxed)) {
      uint64_t target =
          mode == "full"
              ? capacity
              : std::min<uint64_t>(
                    capacity,
                    watermark.load(std::memory_order_relaxed) + headroom);
      if (pos >= target) {
        ::usleep(10000);
        continue;
      }
      // MADV_POPULATE_WRITE faults pages in WITHOUT modifying content,
      // so racing a client's concurrent write into a just-allocated
      // extent is safe by construction (a plain zero-write would not
      // be). On kernels without it, clients simply pay the faults.
      uint64_t chunk = std::min<uint64_t>(8ull << 20, target - pos);
#ifdef MADV_POPULATE_WRITE
      if (::madvise(base + pos, chunk, MADV_POPULATE_WRITE) != 0) break;
#else
      break;
#endif
      pos += chunk;
    }
  }

  bool can_allocate(uint64_t size) const {
    uint64_t want = (size + kAlign - 1) & ~(kAlign - 1);
    if (want == 0) want = kAlign;
    for (const auto& kv : free_list)
      if (kv.second >= want) return true;
    return false;
  }

  uint64_t allocate(uint64_t size) {
    uint64_t want = (size + kAlign - 1) & ~(kAlign - 1);
    if (want == 0) want = kAlign;
    for (auto it = free_list.begin(); it != free_list.end(); ++it) {
      if (it->second >= want) {
        uint64_t off = it->first;
        uint64_t extent = it->second;
        free_list.erase(it);
        if (extent > want) free_list.emplace(off + want, extent - want);
        used += want;
        uint64_t end = off + want;
        uint64_t seen = watermark.load(std::memory_order_relaxed);
        while (end > seen &&
               !watermark.compare_exchange_weak(seen, end)) {
        }
        return off;
      }
    }
    return kInvalid;
  }

  // Caller holds mu (evict calls this mid-scan; the public delete
  // wraps it with the lock).
  bool delete_unlocked(const std::string& id) {
    auto it = entries.find(id);
    if (it == entries.end()) return false;
    release(it->second.offset, it->second.size);
    entries.erase(it);
    return true;
  }

  void release(uint64_t offset, uint64_t size) {
    uint64_t want = (size + kAlign - 1) & ~(kAlign - 1);
    if (want == 0) want = kAlign;
    used -= want;
    auto next = free_list.lower_bound(offset);
    // coalesce with previous extent
    if (next != free_list.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        offset = prev->first;
        want += prev->second;
        free_list.erase(prev);
      }
    }
    // coalesce with next extent
    if (next != free_list.end() && offset + want == next->first) {
      want += next->second;
      free_list.erase(next);
    }
    free_list.emplace(offset, want);
  }
};

}  // namespace

extern "C" {

void* rtpu_store_open(const char* path, uint64_t capacity) {
  int fd = ::open(path, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, (off_t)capacity) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* base = ::mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED,
                      fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Store();
  s->fd = fd;
  s->base = static_cast<uint8_t*>(base);
  s->capacity = capacity;
  s->free_list.emplace(0, capacity);
  // Background page pre-commit: fresh tmpfs pages cost ~0.4ms/MB to
  // allocate+zero at first touch, capping first-write bandwidth near
  // 2 GB/s however the fault is taken. The toucher stays a bounded
  // window ahead of the allocation watermark by default (see
  // toucher_main / RTPU_ARENA_PRECOMMIT) so a mostly-empty store does
  // not become RAM-resident up front.
  s->toucher = std::thread([s] { s->toucher_main(); });
  return s;
}

void rtpu_store_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return;
  s->closing.store(true);
  if (s->toucher.joinable()) s->toucher.join();
  ::munmap(s->base, s->capacity);
  ::close(s->fd);
  delete s;
}

// Returns the object's offset, or UINT64_MAX when allocation fails even
// after evicting every unpinned sealed object (caller then spills).
// Idempotent for an existing id of the same size.
uint64_t rtpu_store_create(void* h, const char* id, uint64_t size) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it != s->entries.end()) {
    if (it->second.size == size) return it->second.offset;
    return kInvalid;
  }
  uint64_t off = s->allocate(size);
  if (off == kInvalid) return kInvalid;
  Entry e;
  e.offset = off;
  e.size = size;
  e.lru = ++s->lru_clock;
  s->entries.emplace(id, e);
  return off;
}

int rtpu_store_seal(void* h, const char* id) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it == s->entries.end()) return -1;
  it->second.sealed = true;
  it->second.lru = ++s->lru_clock;
  return 0;
}

// 0 = found+sealed; 1 = exists but unsealed; -1 = missing.
int rtpu_store_get(void* h, const char* id, uint64_t* offset,
                   uint64_t* size) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it == s->entries.end()) return -1;
  if (!it->second.sealed) return 1;
  it->second.lru = ++s->lru_clock;
  *offset = it->second.offset;
  *size = it->second.size;
  return 0;
}

int rtpu_store_contains(void* h, const char* id) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  return it != s->entries.end() && it->second.sealed ? 1 : 0;
}

int rtpu_store_delete(void* h, const char* id) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->delete_unlocked(id) ? 0 : -1;
}

// Client mapping refcount: objects with refs > 0 are excluded from both
// eviction and spill victim selection (their arena bytes are live in some
// process's address space).
int rtpu_store_addref(void* h, const char* id, int delta) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it == s->entries.end()) return -1;
  int64_t next = (int64_t)it->second.refs + delta;
  it->second.refs = next < 0 ? 0 : (uint32_t)next;
  return (int)it->second.refs;
}

int rtpu_store_pin(void* h, const char* id, int pinned) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it == s->entries.end()) return -1;
  it->second.pinned = pinned != 0;
  return 0;
}

// Evict unpinned sealed objects (LRU-first) until `needed` bytes could be
// allocated. Evicted ids are written as concatenated NUL-terminated hex
// strings into `evicted` (capacity `evicted_cap` bytes). Returns the
// number of evicted objects.
int rtpu_store_evict(void* h, uint64_t needed, char* evicted,
                     uint64_t evicted_cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  int count = 0;
  uint64_t written = 0;
  while (!s->can_allocate(needed)) {
    const std::string* victim = nullptr;
    uint64_t best = ~0ull;
    for (auto& kv : s->entries) {
      if (kv.second.sealed && !kv.second.pinned && kv.second.refs == 0 &&
          kv.second.lru < best) {
        best = kv.second.lru;
        victim = &kv.first;
      }
    }
    if (!victim) break;
    std::string vid = *victim;
    uint64_t len = vid.size() + 1;
    if (written + len <= evicted_cap) {
      std::memcpy(evicted + written, vid.c_str(), len);
      written += len;
    }
    s->delete_unlocked(vid);  // NOT the public fn: mu is already held
    ++s->num_evictions;
    ++count;
  }
  if (written < evicted_cap) evicted[written] = '\0';
  return count;
}

// Least-recently-used pinned sealed object (spill candidate): writes its
// hex id/offset/size; returns 0, or -1 when none exists.
int rtpu_store_lru_pinned(void* h, char* id_out, uint64_t id_cap,
                          uint64_t* offset, uint64_t* size) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  const std::string* victim = nullptr;
  uint64_t best = ~0ull;
  for (auto& kv : s->entries) {
    if (kv.second.sealed && kv.second.pinned && kv.second.refs == 0 &&
        kv.second.lru < best) {
      best = kv.second.lru;
      victim = &kv.first;
    }
  }
  if (!victim) return -1;
  if (victim->size() + 1 > id_cap) return -1;
  std::memcpy(id_out, victim->c_str(), victim->size() + 1);
  auto& e = s->entries[*victim];
  *offset = e.offset;
  *size = e.size;
  return 0;
}

// Debug introspection for tests/diagnostics: out = {found, sealed,
// pinned, refs}.
void rtpu_store_entry_flags(void* h, const char* id, uint64_t out[4]) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->entries.find(id);
  if (it == s->entries.end()) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  out[0] = 1;
  out[1] = it->second.sealed ? 1 : 0;
  out[2] = it->second.pinned ? 1 : 0;
  out[3] = it->second.refs;
}

void rtpu_store_stats(void* h, uint64_t out[4]) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  out[0] = s->capacity;
  out[1] = s->used;
  out[2] = s->entries.size();
  out[3] = s->num_evictions;
}

}  // extern "C"
