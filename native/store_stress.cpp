// Sanitizer harness for the arena store (reference analogue: the tsan/
// asan test jobs over plasma in the reference CI). Exercises the full C
// ABI — create/seal/get/addref/pin/evict/delete plus the background
// pre-commit toucher — from multiple threads, under
// -fsanitize=address,undefined (make sanitize) so memory errors and UB
// surface in CI without hardware.
//
// Exit code 0 = clean run; the sanitizers abort on any finding.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rtpu_store_open(const char* path, uint64_t capacity);
void rtpu_store_close(void* h);
uint64_t rtpu_store_create(void* h, const char* id, uint64_t size);
int rtpu_store_seal(void* h, const char* id);
int rtpu_store_get(void* h, const char* id, uint64_t* offset,
                   uint64_t* size);
int rtpu_store_contains(void* h, const char* id);
int rtpu_store_delete(void* h, const char* id);
int rtpu_store_addref(void* h, const char* id, int delta);
int rtpu_store_pin(void* h, const char* id, int pinned);
int rtpu_store_evict(void* h, uint64_t needed, char* evicted,
                     uint64_t evicted_cap);
int rtpu_store_lru_pinned(void* h, char* id_out, uint64_t id_cap,
                          uint64_t* offset, uint64_t* size);
void rtpu_store_stats(void* h, uint64_t out[4]);
}

static const uint64_t kInvalid = ~0ull;

int main() {
  const char* path = "/tmp/rtpu-sanitize-arena";
  std::remove(path);
  void* store = rtpu_store_open(path, 32ull << 20);  // 32 MiB
  if (!store) {
    std::fprintf(stderr, "open failed\n");
    return 1;
  }

  std::atomic<int> errors{0};
  auto worker = [&](int t) {
    for (int round = 0; round < 200; ++round) {
      std::string id = "obj-" + std::to_string(t) + "-" +
                       std::to_string(round % 17);
      uint64_t size = 4096 + (round % 5) * 1024;
      uint64_t off = rtpu_store_create(store, id.c_str(), size);
      if (off == kInvalid) continue;  // arena momentarily full
      rtpu_store_seal(store, id.c_str());
      uint64_t o = 0, s = 0;
      if (rtpu_store_get(store, id.c_str(), &o, &s)) {
        if (s != size) errors.fetch_add(1);
        rtpu_store_addref(store, id.c_str(), 1);
        rtpu_store_pin(store, id.c_str(), round % 2);
        rtpu_store_pin(store, id.c_str(), 0);
        rtpu_store_addref(store, id.c_str(), -1);
      }
      if (round % 3 == 0) rtpu_store_delete(store, id.c_str());
      rtpu_store_contains(store, id.c_str());
    }
  };

  // NOTE: the store's contract is one client thread per handle method
  // group serialized by the caller (the raylet's single asyncio loop);
  // this harness matches that — threads touch disjoint id namespaces
  // but share the allocator, which is the part the mutex must cover.
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  // Eviction under pressure: fill a tiny arena, then force the evict
  // loop (the path that self-deadlocked when the mutex landed — evict
  // used to re-enter the public delete).
  void* small = rtpu_store_open("/tmp/rtpu-sanitize-small", 1 << 20);
  for (int i = 0; i < 64; ++i) {
    std::string id = "fill-" + std::to_string(i);
    uint64_t off = rtpu_store_create(small, id.c_str(), 64 * 1024);
    if (off != kInvalid) {
      rtpu_store_seal(small, id.c_str());
      if (i % 7 == 0) rtpu_store_pin(small, id.c_str(), 1);
    } else {
      char evicted[4096];
      int n = rtpu_store_evict(small, 64 * 1024, evicted, sizeof evicted);
      if (n <= 0) break;  // everything left is pinned
    }
  }
  char idbuf[256];
  uint64_t o2 = 0, s2 = 0;
  rtpu_store_lru_pinned(small, idbuf, sizeof idbuf, &o2, &s2);
  rtpu_store_close(small);
  std::remove("/tmp/rtpu-sanitize-small");

  uint64_t stats[4];
  rtpu_store_stats(store, stats);
  std::printf("capacity=%llu used=%llu objects=%llu evictions=%llu\n",
              (unsigned long long)stats[0], (unsigned long long)stats[1],
              (unsigned long long)stats[2], (unsigned long long)stats[3]);
  rtpu_store_close(store);
  std::remove(path);
  if (errors.load()) {
    std::fprintf(stderr, "size mismatches: %d\n", errors.load());
    return 1;
  }
  std::puts("SANITIZE-OK");
  return 0;
}
