"""Node/process management — spawns and supervises the cluster daemons.

Role-equivalent to the reference's `_private/node.py` (start_head_processes /
start_ray_processes): the head starts a GCS server process plus a raylet
process; worker nodes start just a raylet. Daemon stdout is parsed for the
bound port (the daemons print ``GCS_PORT=``/``RAYLET_PORT=`` on boot).
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, Optional, Tuple

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import CPU, MEM, OBJECT_STORE_MEM, TPU


def _read_port(proc: subprocess.Popen, marker: str, timeout: float = 30.0) -> int:
    deadline = time.monotonic() + timeout
    buf = b""
    os.set_blocking(proc.stdout.fileno(), False)
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited (code {proc.returncode}) before printing "
                f"{marker}: {buf.decode(errors='replace')}")
        try:
            chunk = proc.stdout.read()
        except (BlockingIOError, TypeError):
            chunk = None
        if chunk:
            buf += chunk
        for line in buf.decode(errors="replace").splitlines():
            if line.startswith(marker):
                os.set_blocking(proc.stdout.fileno(), True)
                return int(line[len(marker):])
        time.sleep(0.01)
    raise TimeoutError(f"daemon did not print {marker} within {timeout}s")


def default_resources(num_cpus: Optional[float] = None,
                      num_tpus: Optional[float] = None,
                      resources: Optional[Dict[str, float]] = None,
                      memory: Optional[int] = None,
                      object_store_memory: Optional[int] = None
                      ) -> Dict[str, float]:
    from ray_tpu.accelerators import tpu as tpu_accel

    out = dict(resources or {})
    out[CPU] = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
    if num_tpus is None:
        num_tpus = tpu_accel.TPUAcceleratorManager.get_current_node_num_accelerators()
    if num_tpus:
        out[TPU] = num_tpus
        out.update(tpu_accel.TPUAcceleratorManager.get_current_node_extra_resources())
    if memory is None:
        try:
            import psutil

            memory = int(psutil.virtual_memory().available * 0.7)
        except Exception:
            memory = 8 * (1024 ** 3)
    out[MEM] = memory
    out[OBJECT_STORE_MEM] = object_store_memory or GlobalConfig.object_store_memory
    return out


class Node:
    """Launches and owns this host's daemons."""

    def __init__(
        self,
        head: bool = True,
        gcs_addr: Optional[Tuple[str, int]] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
        system_config: Optional[Dict] = None,
        session_dir: Optional[str] = None,
        fate_share: bool = True,
        gcs_port: int = 0,
        include_dashboard: bool = False,
    ):
        self.head = head
        self.host = "127.0.0.1"
        # CLI-started nodes (`ray_tpu start`) outlive the starting process;
        # init()-started ones die with their driver.
        self._fate_share = fate_share
        self._gcs_port = gcs_port
        self.node_id = NodeID.from_random()
        self._procs: list = []
        self.session_dir = session_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self._system_config = system_config or {}
        GlobalConfig.initialize(self._system_config)

        if head:
            self.gcs_addr = self._start_gcs()
        else:
            assert gcs_addr is not None
            self.gcs_addr = gcs_addr

        self.resources = default_resources(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            object_store_memory=object_store_memory)
        self.labels = labels or {}
        self.raylet_addr = self._start_raylet(object_store_memory)
        self.dashboard_url: Optional[str] = None
        if head and include_dashboard:
            try:
                self.dashboard_url = self._start_dashboard()
            except Exception as e:
                # Non-essential: a broken dashboard (missing aiohttp,
                # port trouble) must not take the head node down.
                sys.stderr.write(
                    f"[node] dashboard failed to start ({e}); "
                    "continuing without it\n")
        if fate_share:
            atexit.register(self.shutdown)

    # ------------------------------------------------------------------ procs
    def _daemon_env(self):
        env = dict(os.environ)
        env.setdefault("PYTHONUNBUFFERED", "1")
        return env

    def _start_gcs(self) -> Tuple[str, int]:
        log = open(os.path.join(self.session_dir, "logs", "gcs.err"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs_server",
             "--host", self.host, "--port", str(self._gcs_port),
             "--system-config", json.dumps(self._system_config),
             "--session-dir", self.session_dir,
             "--fate-share-pid",
             str(os.getpid() if self._fate_share else 0)],
            stdout=subprocess.PIPE, stderr=log, env=self._daemon_env(),
            start_new_session=True)
        port = _read_port(proc, "GCS_PORT=")
        self._procs.append(proc)
        self._gcs_proc = proc
        return (self.host, port)

    def kill_gcs(self) -> None:
        """Hard-kill the GCS process (fault-injection surface for
        control-plane bounce tests)."""
        self._gcs_proc.kill()
        self._gcs_proc.wait(timeout=10)

    def restart_gcs(self) -> Tuple[str, int]:
        """Restart the GCS on the SAME port, recovering its durable tables
        from the session snapshot (reference: GCS FT via external Redis +
        NotifyGCSRestart; here: file snapshot + raylet re-registration)."""
        if self._gcs_proc.poll() is None:
            self.kill_gcs()
        try:
            self._procs.remove(self._gcs_proc)
        except ValueError:
            pass
        self._gcs_port = self.gcs_addr[1]
        deadline = time.time() + 15
        last = None
        while time.time() < deadline:
            try:
                self.gcs_addr = self._start_gcs()
                return self.gcs_addr
            except RuntimeError as e:   # port briefly in TIME_WAIT
                last = e
                time.sleep(0.5)
        raise last

    def _start_raylet(self, object_store_memory) -> Tuple[str, int]:
        log = open(os.path.join(
            self.session_dir, "logs",
            f"raylet-{self.node_id.hex()[:12]}.err"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.raylet",
             "--host", self.host, "--port", "0",
             "--gcs-host", self.gcs_addr[0],
             "--gcs-port", str(self.gcs_addr[1]),
             "--node-id", self.node_id.hex(),
             "--resources", json.dumps(self.resources),
             "--labels", json.dumps(self.labels),
             "--session-dir", self.session_dir,
             "--object-store-capacity",
             str(object_store_memory or GlobalConfig.object_store_memory),
             "--fate-share-pid",
             str(os.getpid() if self._fate_share else 0)],
            stdout=subprocess.PIPE, stderr=log, env=self._daemon_env(),
            start_new_session=True)
        port = _read_port(proc, "RAYLET_PORT=")
        self._procs.append(proc)
        return (self.host, port)

    def _start_dashboard(self) -> str:
        log = open(os.path.join(self.session_dir, "logs",
                                "dashboard.err"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.dashboard.head",
             "--host", self.host, "--port", "0",
             "--gcs-host", self.gcs_addr[0],
             "--gcs-port", str(self.gcs_addr[1]),
             "--session-dir", self.session_dir,
             "--fate-share-pid",
             str(os.getpid() if self._fate_share else 0)],
            stdout=subprocess.PIPE, stderr=log, env=self._daemon_env(),
            start_new_session=True)
        port = _read_port(proc, "DASHBOARD_PORT=")
        self._procs.append(proc)
        return f"http://{self.host}:{port}"

    # --------------------------------------------------------------- teardown
    def kill_raylet(self):
        """Test hook: kill this node's raylet process (fault injection)."""
        self._procs[-1].kill()

    def shutdown(self, cleanup_session: bool = True):
        import signal

        # SIGTERM first so the raylet can clean its /dev/shm store files...
        for proc in reversed(self._procs):
            if proc.poll() is None:
                try:
                    proc.terminate()
                except Exception:
                    pass
        deadline = time.monotonic() + 2.0
        for proc in self._procs:
            try:
                proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                pass
        # ...then SIGKILL the whole process group (workers included).
        for proc in reversed(self._procs):
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                if proc.poll() is None:
                    try:
                        proc.kill()
                    except Exception:
                        pass
        for proc in self._procs:
            try:
                proc.wait(timeout=3)
            except Exception:
                pass
        self._procs.clear()
        atexit.unregister(self.shutdown)
        if cleanup_session:
            shutil.rmtree(self.session_dir, ignore_errors=True)
