"""Cluster scheduling policies over a replicated resource view.

Role-equivalent to the reference's raylet scheduling data plane
(`scheduling/cluster_resource_scheduler.h`, `policy/hybrid_scheduling_policy.h:29-48`,
spread/node-affinity/node-label/bundle policies). Every raylet (and the GCS,
for actors) holds a `ClusterView` — node_id -> NodeResources — kept in sync by
heartbeat reports, and picks nodes with these pure policies.

Hybrid policy (the default): prefer the local node while its critical resource
utilization is below a threshold; otherwise rank the top-k feasible nodes by
(utilization, node_id) and pick the best — packing at low load, spreading at
high load, deterministic tie-breaks.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.task_spec import SchedulingStrategySpec


class ClusterView:
    """node_id(bytes) -> NodeResources, plus liveness."""

    def __init__(self):
        self.nodes: Dict[bytes, NodeResources] = {}

    def update_node(self, node_id: bytes, resources: NodeResources) -> None:
        self.nodes[node_id] = resources

    def remove_node(self, node_id: bytes) -> None:
        self.nodes.pop(node_id, None)

    def get(self, node_id: bytes) -> Optional[NodeResources]:
        return self.nodes.get(node_id)


def pick_node(
    view: ClusterView,
    demand: ResourceSet,
    strategy: SchedulingStrategySpec,
    local_node_id: Optional[bytes],
    pg_bundle_resources: Optional[ResourceSet] = None,
) -> Optional[bytes]:
    """Returns the chosen node id, or None if no feasible node exists now.

    ``pg_bundle_resources`` replaces ``demand`` when a placement-group
    strategy rewired the demand onto bundle-formatted resources.
    """
    if pg_bundle_resources is not None:
        demand = pg_bundle_resources

    if strategy.kind == "NODE_AFFINITY":
        node = view.get(strategy.node_id)
        if node is not None and node.available.is_superset_of(demand):
            return strategy.node_id
        if strategy.soft:
            return _hybrid(view, demand, local_node_id)
        # Hard affinity: only that node will do; schedulable later if feasible.
        if node is not None and node.is_feasible(demand):
            return None
        return None

    if strategy.kind == "NODE_LABEL":
        candidates = _label_filter(view, strategy.hard_labels)
        # Soft labels only narrow preference WITHIN the hard candidate set.
        if strategy.soft_labels:
            preferred = [n for n in candidates
                         if n in set(_label_filter(view, strategy.soft_labels))]
        else:
            preferred = []
        pool = [n for n in (preferred or candidates)
                if view.nodes[n].available.is_superset_of(demand)]
        if not pool:
            pool = [n for n in candidates
                    if view.nodes[n].available.is_superset_of(demand)]
        return min(pool) if pool else None

    if strategy.kind == "SPREAD":
        return _spread(view, demand)

    return _hybrid(view, demand, local_node_id)


def _label_filter(view: ClusterView, labels: Dict[str, List[str]]) -> List[bytes]:
    out = []
    for node_id, node in view.nodes.items():
        ok = True
        for key, values in labels.items():
            if node.labels.get(key) not in values:
                ok = False
                break
        if ok:
            out.append(node_id)
    return out


def _hybrid(view: ClusterView, demand: ResourceSet,
            local_node_id: Optional[bytes]) -> Optional[bytes]:
    threshold = GlobalConfig.scheduler_spread_threshold
    local = view.get(local_node_id) if local_node_id else None
    if (local is not None and local.available.is_superset_of(demand)
            and local.critical_utilization() < threshold):
        return local_node_id

    feasible = [
        (node.critical_utilization(), node_id)
        for node_id, node in view.nodes.items()
        if node.available.is_superset_of(demand)
    ]
    if not feasible:
        return None
    feasible.sort()
    k = max(1, int(len(view.nodes) * GlobalConfig.scheduler_top_k_fraction))
    util, _ = feasible[0]
    if util < threshold:
        # Pack: lowest utilization, deterministic tie-break.
        return feasible[0][1]
    # Spread regime: random choice among top-k to avoid herd behavior.
    return random.choice(feasible[:k])[1]


def _spread(view: ClusterView, demand: ResourceSet) -> Optional[bytes]:
    feasible = [
        (node.critical_utilization(), node_id)
        for node_id, node in view.nodes.items()
        if node.available.is_superset_of(demand)
    ]
    if not feasible:
        return None
    feasible.sort()
    return feasible[0][1]


def is_feasible_anywhere(view: ClusterView, demand: ResourceSet) -> bool:
    return any(node.is_feasible(demand) for node in view.nodes.values())
