"""Global runtime config registry.

Equivalent in role to the reference's `src/ray/common/ray_config_def.h` macro
table (218 `RAY_CONFIG(type, name, default)` entries): a single source of truth
of typed, defaulted knobs, each overridable by an environment variable
``RAY_TPU_<name>`` on any process, or by a ``_system_config`` dict passed to
``ray_tpu.init`` on the head node and propagated to every other process through
the GCS at registration time.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclass
class _ConfigEntry:
    name: str
    type: type
    default: Any
    doc: str = ""


_REGISTRY: Dict[str, _ConfigEntry] = {}


def _define(name: str, type_: type, default: Any, doc: str = "") -> None:
    _REGISTRY[name] = _ConfigEntry(name, type_, default, doc)


# ---------------------------------------------------------------------------
# Config table. Names intentionally parallel the reference's where the knob is
# the same concept (e.g. max_direct_call_object_size ~ ray_config_def.h:206).
# ---------------------------------------------------------------------------

# --- object store / objects ---
_define("max_direct_call_object_size", int, 100 * 1024,
        "Objects <= this many bytes are inlined in task replies / the "
        "in-process memory store instead of the shared-memory store.")
_define("object_store_memory", int, 2 * 1024 * 1024 * 1024,
        "Default per-node shared-memory object store capacity in bytes.")
_define("object_manager_chunk_size", int, 5 * 1024 * 1024,
        "Chunk size for node-to-node object transfer.")
_define("object_spilling_threshold", float, 0.8,
        "Fraction of store capacity above which primary copies spill to disk.")
_define("object_store_fallback_directory", str, "",
        "Directory for disk spillover; defaults under the session dir.")
_define("rpc_put_max_bytes", int, 512 * 1024,
        "Owner puts <= this many bytes travel inside a single pipelined "
        "put_object RPC; larger ones are written into the shared arena "
        "mapping directly (create + client memcpy + seal).")
_define("async_put_max_inflight", int, 32,
        "Max owner puts pipelined on the io loop before put() blocks.")

# --- scheduling ---
_define("scheduler_top_k_fraction", float, 0.2,
        "Hybrid scheduling policy considers the top max(1, k*n_nodes) nodes.")
_define("scheduler_spread_threshold", float, 0.5,
        "Critical resource utilization below which the hybrid policy packs "
        "onto the local/first node instead of spreading.")
_define("worker_lease_timeout_ms", int, 30000, "")
_define("actor_unreachable_timeout_s", float, 120.0,
        "How long the actor delivery layer keeps resending the same "
        "frames (same seqs — dedup'd by the worker) to an actor that is "
        "ALIVE with an unchanged incarnation but unreachable, before "
        "surfacing ActorUnavailableError. Oversubscribed hosts can "
        "CPU-starve healthy workers past many connect timeouts.")
_define("max_workers_per_node", int, 0,
        "Cap on pooled workers per node; 0 means #CPUs.")
_define("worker_pool_idle_ttl_s", float, 600.0,
        "Idle pooled workers beyond the soft limit are reaped after this.")

# --- fault tolerance ---
_define("health_check_period_ms", int, 1000, "")
_define("raylet_report_resources_period_ms", int, 100,
        "How often a raylet pushes its resource view to the GCS. Drives how "
        "fast spillback decisions see remote availability (reference: "
        "raylet_report_resources_period_milliseconds).")
_define("health_check_failure_threshold", int, 5,
        "Consecutive missed health checks before a node is marked dead.")
_define("task_max_retries_default", int, 3, "")
_define("borrow_pending_ttl_s", float, 600.0,
        "How long a serialized-out ref stays pinned waiting for its "
        "recipient to register as a borrower. The backstop that turns "
        "lost-message races into a bounded delay instead of a leak.")
_define("actor_max_restarts_default", int, 0, "")

# --- rpc / transport ---
_define("rpc_connect_timeout_s", float, 10.0, "")
_define("rpc_call_timeout_s", float, 120.0, "")
_define("gcs_rpc_port", int, 0, "0 = pick a free port.")

# --- workers ---
_define("worker_register_timeout_s", float, 30.0, "")
_define("worker_startup_batch", int, 4, "Prestarted workers per node.")
_define("object_store_backend", str, "native",
        "Per-node store backend: 'native' (C++ arena allocator, "
        "native/arena_store.cpp) or 'files' (file-per-object fallback).")
_define("worker_pool_min_idle", int, 2,
        "Keep at least this many warm workers per active job so actor "
        "creation after kills never pays a Python cold start "
        "(reference: worker_pool.cc prestart).")

# --- memory monitor / OOM (reference: memory_monitor.h:52,
# worker_killing_policy.h:34; threshold default mirrors
# RAY_memory_usage_threshold) ---
_define("memory_usage_threshold", float, 0.95,
        "Node memory fraction above which the raylet OOM-kills a leased "
        "task worker (retriable-newest-first policy).")
_define("memory_monitor_refresh_ms", int, 250,
        "Memory monitor poll period; 0 disables OOM killing.")
_define("memory_monitor_test_usage_path", str, "",
        "Test hook: read the usage fraction from this file instead of "
        "psutil/cgroup.")
_define("memory_preempt_threshold", float, 0.85,
        "Node memory fraction above which the raylet preemptively "
        "retires the largest leased task worker (PREEMPT_RESCHEDULE; "
        "the task retries via the normal lease-return path) before the "
        "kill threshold is reached. Must sit below "
        "memory_usage_threshold; 0 disables preemption.")
_define("memory_preempt_cooldown_s", float, 5.0,
        "Minimum spacing between memory preemptions on one node — one "
        "retirement must get a chance to free memory before the next "
        "verdict.")

# --- metrics-driven control plane ---
_define("ctrl_metrics_staleness_s", float, 10.0,
        "A controller reading whose newest source push is older than "
        "this holds (no action) instead of acting — 'the gauge is low' "
        "and 'the gauge stopped updating' must never be conflated.")
_define("ctrl_decisions_buffer_size", int, 2_000,
        "Ring buffer capacity of the GCS control-decision log "
        "(GET /api/controller).")
_define("serve_autoscale_interval_s", float, 2.0,
        "Period of the serve controller's autoscale policy loop (each "
        "tick refreshes the MetricsHub and re-evaluates desired "
        "replicas; jittered ±20% to avoid thundering herds).")
_define("serve_autoscale_cooldown_s", float, 5.0,
        "Minimum spacing between scale actions on one deployment, on "
        "top of the up/downscale hold delays.")
_define("serve_kv_block_size", int, 16,
        "Default paged-KV block size (rows per HBM block) for serve "
        "LLM engines built without an explicit kv_block_size.")
_define("serve_router_probe_interval_s", float, 1.0,
        "Period of the LLM router's per-replica queue-depth probe; a "
        "stalled replica sheds traffic within about one period.")
_define("serve_preempt_hold_s", float, 0.25,
        "How long the interactive lane must stay starved (queued "
        "request + no admissible slot) before the engine's Hysteresis "
        "gate lets it checkpoint a batch decode — transient pressure "
        "from one full tick never thrashes checkpoints.")
_define("serve_preempt_cooldown_s", float, 1.0,
        "Minimum spacing between batch-decode preemptions on one "
        "engine (each checkpoint costs an export + a later re-adopt).")
_define("serve_spec_k", int, 4,
        "Speculative decoding depth for serve LLM engines built with a "
        "draft model: spec_k - 1 draft proposals verified per round, "
        "so each verify step emits 1..spec_k tokens.")
_define("serve_kv_host_tier_bytes", int, 256 * 1024 * 1024,
        "Host-RAM budget of the KV memory hierarchy's middle tier "
        "(serve/llm/kv_cache.KVTierManager): evicted prefix blocks "
        "spill here instead of vanishing; overflow demotes to the "
        "object store (or is dropped, counted, when no cluster is "
        "attached).")
_define("serve_kv_adopt_cost_fixed_ms", float, 2.0,
        "PromoteCostModel: fixed cost of one tier->HBM promote "
        "dispatch (host staging + the adopt scatter launch), "
        "independent of block count.")
_define("serve_kv_adopt_cost_per_block_ms", float, 0.1,
        "PromoteCostModel: marginal cost per promoted KV block "
        "(host->device transfer of one block's rows).")
_define("serve_kv_prefill_cost_per_token_ms", float, 0.05,
        "PromoteCostModel: prefill cost per prompt token — the "
        "recompute side of the promote-vs-recompute crossover. Short "
        "suffixes recompute; long ones re-adopt.")
_define("serve_prefix_index_publish_interval_s", float, 2.0,
        "Period of each LLM replica's prefix-index publish (hash-chain "
        "heads + tier residency -> GCS report_prefix_index).")
_define("serve_prefix_index_ttl_s", float, 15.0,
        "GCS prefix-index entry lifetime: a replica that stops "
        "publishing drops out of cache-aware routing after this long "
        "(and the router HOLDs to plain p2c per the staleness "
        "discipline when its whole view is older than this).")
_define("serve_prefix_index_max_heads", int, 512,
        "Cap on hash-chain heads one replica publishes per index "
        "report (hottest first; the index is a routing hint, not a "
        "directory).")
_define("serve_router_cache_weight", float, 0.25,
        "Cache-aware p2c: score = load - weight * expected prefix-hit "
        "blocks. Keep < 1 so affinity breaks near-ties without "
        "outweighing whole queued requests (BENCH llama_serve_kv_"
        "tiering: weight 1.0 saturates the hot family's replica and "
        "queue wait eats the prefill savings). 0 recovers plain "
        "queue-depth p2c.")
_define("serve_peer_pull_min_blocks", int, 4,
        "Minimum expected-hit advantage (in blocks) a peer must hold "
        "over the chosen replica before the router pulls KV blocks "
        "from it instead of letting the replica recompute.")
_define("serve_accounting_instrumentation", bool, True,
        "Per-request cost accounting on serve LLM engines "
        "(observability.accounting.RequestMeter): prefill tokens "
        "computed vs avoided, decode tokens, KV block-seconds, "
        "chip-seconds per phase, folded into the tenant ledger and "
        "published to the GCS accounting ring. Off = the unmetered "
        "engine; the serve_accounting_overhead bench prices the delta.")
_define("serve_accounting_buffer_size", int, 4096,
        "Bound on the GCS serve-accounting ring "
        "(report_serve_accounting / list_serve_accounting rows across "
        "all replicas).")
_define("serve_accounting_top_n", int, 8,
        "How many tenants the accounting summaries rank by cost "
        "(serve_accounting_summary / GET /api/accounting top lists).")
_define("serve_accounting_max_tenants", int, 64,
        "Bound on distinct tenant rows a TenantLedger holds; overflow "
        "tenants fold into the '__other__' rollup row, which also caps "
        "the cardinality of the rtpu_serve_tenant_* counter label.")
_define("serve_slo_ttft_ms", str, "interactive=500,*=2000",
        "Per-lane TTFT targets (ms) for SLO attainment: "
        "'lane=ms,...' with '*' as the default lane. A bare number "
        "applies to every lane.")
_define("serve_slo_tpot_ms", str, "interactive=200,*=1000",
        "Per-lane TPOT (per-output-token) targets in ms; same format "
        "as serve_slo_ttft_ms.")
_define("serve_slo_objective", float, 0.99,
        "Fraction of requests per lane that must meet their TTFT/TPOT "
        "targets; 1 - objective is the error budget the burn rate is "
        "measured against.")
_define("serve_slo_burn_fast_window_s", float, 60.0,
        "Fast window of the multi-window SLO burn-rate evaluation "
        "(catches sharp regressions within about a minute).")
_define("serve_slo_burn_slow_window_s", float, 3600.0,
        "Slow window of the SLO burn-rate evaluation (the fast window "
        "only fires when the slow window is also consuming budget, so "
        "a one-blip spike never pages).")
_define("serve_slo_burn_threshold", float, 10.0,
        "Fast-window burn rate at or above which (with the slow "
        "window also >= 1.0) an SLO_BURN cluster event fires; the "
        "episode clears when the fast burn drops below half this.")
_define("serve_slo_min_samples", int, 3,
        "Minimum fast-window observations before a lane's burn rate "
        "is trusted enough to fire SLO_BURN.")
_define("data_backpressure_interval_s", float, 1.0,
        "Minimum spacing between backpressure re-evaluations per "
        "executor (the tuner is pulled from the launch loop; this "
        "bounds its decision rate).")
_define("data_backpressure_max_scale", float, 4.0,
        "Upper bound on the backpressure tuner's multiplier over an "
        "executor's base inflight/queued limits (lower bound is the "
        "reciprocal).")

# --- decoupled RL (podracer) ---
_define("rl_weight_history", int, 4,
        "Versions the WeightStore registry retains; older wrapped refs "
        "are dropped (subscribers more than this many versions behind "
        "must fall forward to latest).")
_define("rl_infer_batch_wait_s", float, 0.003,
        "Inference-server gather window: how long a batch collects "
        "concurrent infer() submissions before the jitted forward "
        "runs.")
_define("rl_weight_poll_interval_s", float, 0.1,
        "Base period of an inference server's weight-channel poll "
        "(jittered ±20% so a server fleet does not stampede the "
        "registry).")
_define("rl_sample_queue_maxsize", int, 8,
        "Bound of the sample queue between acting and learning; a "
        "full queue throttles producers (backpressure) instead of "
        "buffering without limit.")
_define("rl_staleness_clip", int, 4,
        "Max published-minus-behavior weight versions before a sample "
        "batch is dropped by the learner pool instead of applied.")

# --- logging / events ---
_define("event_stats", bool, True,
        "Track per-handler latency stats on runtime event loops.")
_define("task_events_buffer_size", int, 100_000,
        "Ring buffer capacity of task lifecycle events kept on the head "
        "(reference: gcs task manager ring buffer).")
_define("cluster_events_buffer_size", int, 10_000,
        "Ring buffer capacity of the GCS ClusterEventLog (typed "
        "failure-forensics events; reference: gcs event export).")
_define("worker_exit_tail_lines", int, 20,
        "How many trailing log lines the raylet captures from a dead "
        "worker's stdout/stderr files for death-error enrichment.")
_define("metrics_report_interval_s", float, 2.0,
        "Flush cadence of user-defined ray_tpu.util.metrics to the GCS "
        "(reference: metrics_report_interval_ms).")
_define("trace_sample_rate", float, 0.01,
        "Tail-sampling keep probability for fast, clean traces in the "
        "GCS TraceStore. Slow (>= trace_keep_threshold_s) and errored "
        "traces are always kept — the decision runs at trace "
        "completion, when the whole trace is visible.")
_define("trace_keep_threshold_s", float, 0.5,
        "Root-span duration at or above which a completed trace is "
        "always kept regardless of trace_sample_rate.")
_define("trace_store_maxlen", int, 512,
        "LRU capacity of kept traces in the GCS TraceStore.")
_define("trace_pending_max", int, 2048,
        "Bound on in-flight (rootless) traces accumulating in the "
        "TraceStore; oldest-first eviction, so a crashed hop that "
        "never sends its root span cannot leak memory.")
_define("sched_phase_instrumentation", bool, True,
        "Record per-task scheduling-phase timestamps (PENDING -> "
        "LEASE_GRANTED -> WORKER_STARTED -> ARGS_READY -> RUNNING) "
        "through the lease protocol: task-event ring entries, segmented "
        "timeline submit arrows, and the rtpu_sched_phase_seconds{phase} "
        "histogram. Off = only the PENDING/RUNNING/FINISHED skeleton.")
_define("profiler_default_hz", int, 100,
        "Default sampling rate of the wall-clock stack profiler "
        "(observability.profiling.StackSampler / util.state.profile).")
_define("profiler_max_unique_stacks", int, 10_000,
        "Bound on distinct (thread, stack) keys one StackSampler run "
        "retains; overflowing samples are counted as dropped instead of "
        "allocated, so profiling can never OOM the target.")
_define("profiler_max_duration_s", float, 60.0,
        "Cap on a single worker-side profile RPC window (long profiles "
        "are chunked by the util.state.profile client).")
_define("tpu_profile_dir", str, "",
        "Directory for util.state.tpu_profile jax.profiler artifacts; "
        "defaults under the system temp dir.")
_define("train_goodput_instrumentation", bool, True,
        "Per-step train phase ledger + goodput accounting "
        "(observability.goodput): rtpu_train_step_phase_seconds{phase} "
        "histograms, the rtpu_train_goodput_ratio gauge, train.step "
        "spans, and step-row heartbeats into the GCS step matrix "
        "(report_train_steps). Off = the uninstrumented step loop; the "
        "train_goodput_overhead bench prices the delta.")
_define("train_steps_buffer_size", int, 4096,
        "Bound on the GCS train-step matrix ring (report_train_steps/"
        "list_train_steps rows across all workers).")
_define("train_straggler_threshold", float, 1.5,
        "A train worker whose windowed mean step time exceeds the pod "
        "median by this factor is flagged with a TRAIN_STRAGGLER "
        "cluster event naming its dominant phase.")
_define("train_straggler_window", int, 8,
        "Per-worker window (steps) of the straggler detector's means; "
        "also the re-flag suppression distance (one event per "
        "straggler episode, not one per step).")
_define("train_stall_heartbeats", int, 3,
        "A train worker missing this many expected step-report "
        "heartbeats (expected interval = its recent median step time) "
        "is declared stalled: TRAIN_STALL event + automatic "
        "dump_stacks capture of the worker attached to the event.")
_define("train_stall_min_timeout_s", float, 10.0,
        "Floor on the stall watchdog timeout, so fast steps (ms-class "
        "on the CPU tier) don't declare a stall on scheduler jitter.")
_define("train_stall_check_interval_s", float, 1.0,
        "Period of the GCS train stall watchdog sweep.")
_define("xla_attribution_instrumentation", bool, True,
        "Per-program XLA cost attribution on tracked_jit wrappers "
        "(observability.xla.ProgramRegistry): cost_analysis/"
        "memory_analysis capture on compile, MFU/MBU + roofline "
        "verdicts from sampled walls, rows into the GCS "
        "report_xla_programs ring, and the PERF_REGRESSION sentinel. "
        "Off = plain trace/compile counters only; the "
        "xla_attribution_overhead bench prices the delta.")
_define("xla_wall_sample_every", int, 64,
        "Sample every Nth steady-state call of a tracked jitted "
        "function with block_until_ready to measure an honest "
        "execution wall (feeds MFU/MBU). 0 disables wall sampling — "
        "no fence ever runs on the hot path; rows then carry cost/"
        "memory analysis but no utilization ratios.")
_define("xla_programs_buffer_size", int, 4096,
        "Bound on the GCS XLA program ring (report_xla_programs / "
        "list_xla_programs rows across all processes).")
_define("xla_regression_ratio", float, 1.5,
        "Regression sentinel threshold: a re-compile whose flops or "
        "peak HBM bytes — or a sampled wall whose EWMA — exceeds the "
        "function's baseline by this factor fires one PERF_REGRESSION "
        "cluster event per drifted-dimension episode (re-arms when the "
        "dimension returns within the ratio). 0 disables the sentinel.")
_define("xla_comm_bound_fraction", float, 0.5,
        "Exposed-collective fraction of a sampled program wall above "
        "which the roofline verdict is 'comm-bound' instead of "
        "compute-/memory-bound (fed by the split-phase overlap "
        "accounting in observability.collective).")
_define("jit_recompile_warn_budget", int, 8,
        "Default trace budget of observability.tracked_jit wrappers: a "
        "tracked jitted function that traces more programs than this "
        "warns RecompileWarning once (silent XLA retracing is the #1 "
        "TPU perf killer). Explicit trace_budget= overrides per "
        "wrapper; 0 disables the warning.")

# --- tpu ---
_define("tpu_chips_per_host_default", int, 4, "")
_define("fake_tpu_hosts", int, 0,
        "If >0, accelerator detection fakes this many TPU hosts for tests.")


class _Config:
    """Resolved view: env var > system_config > default."""

    def __init__(self):
        self._system_config: Dict[str, Any] = {}

    def initialize(self, system_config: Dict[str, Any] | None) -> None:
        if not system_config:
            return
        for key, value in system_config.items():
            if key not in _REGISTRY:
                raise ValueError(f"Unknown system config key: {key}")
            self._system_config[key] = value

    def get(self, name: str) -> Any:
        entry = _REGISTRY[name]
        env_val = os.environ.get(_ENV_PREFIX + name)
        if env_val is not None:
            return _PARSERS[entry.type](env_val)
        if name in self._system_config:
            return entry.type(self._system_config[name])
        return entry.default

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None

    def dump_system_config(self) -> str:
        return json.dumps(self._system_config)

    def load_system_config(self, payload: str) -> None:
        self._system_config.update(json.loads(payload))


GlobalConfig = _Config()
