"""ObjectRef — the handle to a (possibly pending) immutable object.

Reference-counted by the owning worker: creating and destroying Python
ObjectRef instances adjusts the owner's local refcount (reference:
`src/ray/core_worker/reference_count.h:61`). Serializing a ref into a task
argument or another object enters the borrower protocol (see
`reference_count.py`): the recipient registers with the owner and the
object is freed once every borrower drains.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_owner_worker_id", "_registered",
                 "__weakref__")

    def __init__(self, object_id: bytes, owner_addr: Tuple[str, int],
                 owner_worker_id: bytes, _register: bool = True):
        self._id = object_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._owner_worker_id = owner_worker_id
        self._registered = False
        if _register:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is not None:
                w.reference_counter.add_local_ref(self._id)
                self._registered = True

    # -- identity -----------------------------------------------------------
    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner_addr(self) -> Optional[Tuple[str, int]]:
        return self._owner_addr

    @property
    def owner_worker_id(self) -> bytes:
        return self._owner_worker_id

    def object_id(self) -> ObjectID:
        return ObjectID(self._id)

    def task_id(self):
        return ObjectID(self._id).task_id()

    # -- lifecycle ----------------------------------------------------------
    def __del__(self):
        if not self._registered:
            return
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is not None:
                # NEVER decref inline: __del__ can fire inside any
                # allocation on a thread already holding worker locks
                # (self-deadlock via _free_object). deque.append is the
                # only GC-safe operation; the worker drains it at entry
                # points and from its release-drainer task.
                w.defer_release(self._id)
        except BaseException:
            # Interpreter teardown: module globals may already be gone.
            pass

    # -- hashing / equality -------------------------------------------------
    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self.hex()})"

    # -- awaitable ----------------------------------------------------------
    def __await__(self):
        """``await ref`` resolves to the object's VALUE (reference
        semantics), not the one-element list ``async_get`` returns."""
        from ray_tpu._private import worker as worker_mod

        async def _resolve():
            values = await worker_mod.global_worker().async_get([self])
            return values[0]

        return _resolve().__await__()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        import concurrent.futures
        import threading

        from ray_tpu._private import worker as worker_mod

        fut: concurrent.futures.Future = concurrent.futures.Future()
        w = worker_mod.global_worker()

        def _wait():
            try:
                fut.set_result(w.get_objects([self], timeout=None)[0])
            except BaseException as exc:  # noqa: BLE001
                fut.set_exception(exc)

        threading.Thread(target=_wait, daemon=True).start()
        return fut


# Thread-local capture of refs crossing a serialize/deserialize boundary,
# feeding the borrower protocol (reference: borrowed-ref bookkeeping in
# `reference_count.cc`). The serializer/worker installs a list before the
# (de)pickling pass and collects it after.
_capture = threading.local()


def begin_serialize_capture() -> None:
    _capture.out = []


def end_serialize_capture():
    out = getattr(_capture, "out", None)
    _capture.out = None
    return out or []


def begin_deserialize_capture() -> None:
    _capture.inb = []


def end_deserialize_capture():
    inb = getattr(_capture, "inb", None)
    _capture.inb = None
    return inb or []


def reduce_object_ref(ref: ObjectRef):
    """Pickle reducer: pin with a pending share (a recipient will claim
    it by registering as a borrower, or the TTL sweep expires it), and
    rehydrate on load."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is not None:
        w.reference_counter.add_pending_share(ref.binary())
    out = getattr(_capture, "out", None)
    if out is not None:
        out.append((ref.binary(), ref.owner_addr))
    return _rehydrate_ref, (ref.binary(), ref.owner_addr, ref.owner_worker_id)


def _rehydrate_ref(object_id, owner_addr, owner_worker_id):
    ref = ObjectRef(object_id, owner_addr, owner_worker_id)
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is not None and owner_addr is not None:
        if tuple(owner_addr) != w.addr:
            w.reference_counter.add_borrowed(object_id, tuple(owner_addr))
            inb = getattr(_capture, "inb", None)
            if inb is not None:
                inb.append((object_id, tuple(owner_addr)))
        # else: the bytes came home to the owner. Do NOT consume a pending
        # share here — shares are fungible per object, and the one we'd
        # pop could be the only pin covering a different still-in-flight
        # copy; the TTL sweep retires it instead.
    return ref


class ObjectRefGenerator:
    """Iterator over the return refs of a generator task
    (``num_returns="streaming"``).

    Reference: `python/ray/_raylet.pyx:272` (ObjectRefGenerator): the remote
    call returns this handle immediately; item refs become available
    incrementally as the executing worker reports them
    (ReportGeneratorItemReturns — here the `report_generator_item` owner
    RPC). Iterating blocks until the next item exists or the generator
    finishes (StopIteration). Only usable in the owner process.
    """

    def __init__(self, task_id: bytes, owner_addr, owner_worker_id: bytes):
        self._task_id = task_id
        self._owner_addr = tuple(owner_addr)
        self._owner_worker_id = owner_worker_id
        self._next_index = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from ray_tpu._private import worker as worker_mod

        ref = worker_mod.global_worker().next_generator_ref(
            self._task_id, self._next_index)
        self._next_index += 1
        return ref

    def completed(self) -> int:
        """Number of item refs produced so far."""
        from ray_tpu._private import worker as worker_mod

        return worker_mod.global_worker().generator_progress(self._task_id)[0]

    def __repr__(self):
        return f"ObjectRefGenerator(task={self._task_id.hex()})"
