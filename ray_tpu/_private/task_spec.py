"""Task specifications — the unit handed from submitters to schedulers.

Role-equivalent to the reference's `src/ray/common/task/task_spec.h` +
`function_descriptor.h`. A TaskSpec is fully picklable and self-contained:
function descriptor (resolved against the GCS function table), serialized
args (inline values or ObjectRef descriptors), resource demand, scheduling
strategy, and retry/return metadata (option surface mirrors
`python/ray/_private/ray_option_utils.py`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.resources import ResourceSet


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class FunctionDescriptor:
    """Identifies a remote function/actor class in the GCS function table."""

    module: str
    qualname: str
    function_hash: str  # content hash; key in the GCS KV function table

    def key(self) -> str:
        return f"fn:{self.function_hash}"

    def __repr__(self):
        return f"{self.module}.{self.qualname}"

    def __reduce__(self):
        # Positional-tuple pickling: specs cross the wire on every task
        # submission — dict-based dataclass pickling repeats every field
        # name per instance and is ~3x larger and slower.
        return (FunctionDescriptor,
                (self.module, self.qualname, self.function_hash))


@dataclass
class ArgSpec:
    """One task argument: either an inline serialized value or an ObjectRef."""

    is_ref: bool
    # inline payload (SerializedObject bytes) when not a ref
    inline_data: Optional[bytes] = None
    # object id + owner address when a ref
    object_id: Optional[bytes] = None
    owner_addr: Optional[Tuple[str, int]] = None

    def __reduce__(self):
        return (ArgSpec, (self.is_ref, self.inline_data, self.object_id,
                          self.owner_addr))


@dataclass
class SchedulingStrategySpec:
    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP | NODE_LABEL
    node_id: Optional[bytes] = None
    soft: bool = False
    placement_group_id: Optional[bytes] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    hard_labels: Dict[str, List[str]] = field(default_factory=dict)
    soft_labels: Dict[str, List[str]] = field(default_factory=dict)

    def __reduce__(self):
        return (SchedulingStrategySpec,
                (self.kind, self.node_id, self.soft,
                 self.placement_group_id, self.bundle_index,
                 self.capture_child_tasks, self.hard_labels,
                 self.soft_labels))


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function: FunctionDescriptor
    args: List[ArgSpec]
    kwargs_keys: List[str]  # last len(kwargs_keys) args are kwargs
    num_returns: int
    resources: ResourceSet
    owner_addr: Tuple[str, int]  # core-worker RPC address of the owner
    owner_worker_id: WorkerID
    name: str = ""
    scheduling: SchedulingStrategySpec = field(default_factory=SchedulingStrategySpec)
    max_retries: int = 0
    retry_exceptions: Any = False  # bool or list of exception types (pickled ok)
    runtime_env: Optional[Dict[str, Any]] = None
    # actor tasks
    actor_id: Optional[ActorID] = None
    sequence_number: int = -1
    concurrency_group: str = ""
    # actor creation
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    is_async_actor: bool = False
    is_detached: bool = False
    actor_name: str = ""
    namespace: str = ""
    # generators
    is_streaming_generator: bool = False
    generator_backpressure: int = -1
    # tracing
    parent_task_id: Optional[TaskID] = None
    depth: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    # Owner-side scheduling-phase timestamps (PENDING / LEASE_GRANTED
    # wall clocks; see observability.profiling.SCHED_PHASES). Stashed on
    # the spec rather than a side table so the stash dies with the task
    # — retries reuse the same spec object and keep the original submit
    # time. Rides the wire as a small dict; executing workers ignore it.
    phase_ts: Optional[Dict[str, float]] = None
    # Caller's request-scoped trace context (TraceContext.to_wire():
    # {"t": trace_id, "s": span_id, "b": baggage}) — the executing
    # worker restores it around the task body so spans recorded
    # downstream parent under the span active at submit time. Appended
    # last: __reduce__ tolerates missing trailing fields, so old specs
    # deserialize with trace_ctx=None.
    trace_ctx: Optional[Dict[str, Any]] = None

    def __reduce__(self):
        return (_rebuild_task_spec, tuple(
            getattr(self, f) for f in _TASK_SPEC_FIELDS))

    def return_ids(self) -> List[ObjectID]:
        # Generator tasks (num_returns < 0: -1 dynamic, -2 streaming) have
        # one visible return — the generator ref at index 1; yielded items
        # take indices 2, 3, ... as they are produced.
        n = 1 if self.num_returns < 0 else self.num_returns
        return [
            ObjectID.for_task_return(self.task_id, i + 1)
            for i in range(n)
        ]

    def generator_item_id(self, item_index: int) -> ObjectID:
        return ObjectID.for_task_return(self.task_id, item_index + 2)

    def dependencies(self) -> List[bytes]:
        return [a.object_id for a in self.args if a.is_ref]


_TASK_SPEC_FIELDS = tuple(f.name for f in TaskSpec.__dataclass_fields__.values())


def _rebuild_task_spec(*values) -> TaskSpec:
    # Tolerates fields appended in newer versions: missing trailing values
    # fall back to declared defaults (positional prefix construction).
    return TaskSpec(*values)
