"""Node memory watchdog (reference: `src/ray/common/memory_monitor.h:52`
+ `raylet/worker_killing_policy.h:34`).

The reference polls cgroup/system memory inside the raylet and, above a
usage threshold, kills workers by policy — retriable tasks first, newest
first — so one leaky task degrades to a retry instead of the kernel OOM
killer taking down the raylet or an actor holding TPU chips.

Usage source order: the test-injection file (if configured), cgroup v2
`memory.current/memory.max` (container limits beat host totals), then
psutil virtual memory.
"""

from __future__ import annotations

import os
from typing import Optional

_CGROUP_CUR = "/sys/fs/cgroup/memory.current"
_CGROUP_MAX = "/sys/fs/cgroup/memory.max"


def usage_fraction(test_path: str = "") -> Optional[float]:
    """Current memory usage in [0, 1], or None if undeterminable."""
    if test_path:
        try:
            with open(test_path) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return None
    try:
        with open(_CGROUP_CUR) as f:
            cur = int(f.read())
        with open(_CGROUP_MAX) as f:
            raw = f.read().strip()
        if raw != "max":
            limit = int(raw)
            if limit > 0:
                return cur / limit
    except (OSError, ValueError):
        pass
    try:
        import psutil

        return psutil.virtual_memory().percent / 100.0
    except Exception:
        return None


def pick_victim(workers, busy_ids=frozenset(),
                rss=None) -> Optional[object]:
    """Worker-killing policy over _WorkerHandle values: leased task
    workers before actors (tasks retry for free; actors lose state);
    within a class, workers actually executing before idle-leased ones
    (killing a pool-idle worker frees no task memory); then largest
    resident set first — the kill should be attributed to the worker
    actually holding the memory, not whichever leased newest (observed:
    newest-lease-first shooting a 50 MB bystander while a 4 GB hog kept
    thrashing). ``busy_ids`` is the set of worker_ids observed executing
    (raylet probes `busy_info`); ``rss`` maps worker_id -> resident
    bytes (missing entries rank lowest). Lease recency breaks ties."""
    leased = [h for h in workers if h.lease is not None]
    if not leased:
        return None
    tasks = [h for h in leased if not h.is_actor]
    pool = tasks or leased
    rss = rss or {}
    return max(pool, key=lambda h: (getattr(h, "worker_id", None) in busy_ids,
                                    rss.get(getattr(h, "worker_id", None),
                                            0.0),
                                    getattr(h, "lease_ts", 0.0)))
