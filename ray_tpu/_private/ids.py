"""Structured binary IDs for the ray_tpu runtime.

Design follows the reference ID nesting scheme (ray `src/ray/common/id.h`,
`src/ray/design_docs/id_specification.md:1`): JobID (4B) is a suffix of
ActorID (16B), which is a suffix of TaskID (24B), which is a prefix of
ObjectID (28B = TaskID + 4B return-index).  This lets any component recover
the job from an actor, the actor from a task, and the creating task from an
object with pure byte slicing — no lookups.

All IDs are immutable value types, hashable, and serialize as raw bytes.
"""

from __future__ import annotations

import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 16
_TASK_ID_SIZE = 24
_OBJECT_ID_SIZE = 28
_UNIQUE_ID_SIZE = 28  # NodeID / WorkerID / PlacementGroupID


class BaseID:
    """Common machinery for fixed-size binary IDs."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash((type(self).__name__, self._bytes))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class ActorID(BaseID):
    """16 bytes: 12 random + 4 job-id suffix."""

    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        return cls(b"\xff" * (cls.SIZE - JobID.SIZE) + job_id.binary())

    def is_nil(self) -> bool:
        # Normal tasks carry nil_for_job (0xff prefix + job suffix).
        n = self.SIZE - JobID.SIZE
        return self._bytes[:n] == b"\xff" * n

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class TaskID(BaseID):
    """24 bytes: 8 unique + 16 actor-id suffix."""

    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(
            os.urandom(cls.SIZE - ActorID.SIZE)
            + ActorID.nil_for_job(job_id).binary()
        )

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(cls.SIZE - ActorID.SIZE) + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(b"\x00" * (cls.SIZE - ActorID.SIZE) + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[-ActorID.SIZE:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    """28 bytes: 24-byte creating TaskID + 4-byte little-endian return index."""

    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid collision with
        # return indices (reference: ObjectID::FromIndex with negative index).
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE:], "little") & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(self._bytes[-1] & 0x80)

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class UniqueID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class NodeID(UniqueID):
    pass


class WorkerID(UniqueID):
    pass


class PlacementGroupID(BaseID):
    """16 bytes: 12 random + 4 job-id suffix (mirrors ActorID layout)."""

    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class _IndexCounter:
    """Thread-safe monotonically increasing counter (per-worker task/put index)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
