"""Object serialization with zero-copy out-of-band buffers.

Role-equivalent to the reference's `_private/serialization.py:110`
(`SerializationContext`): cloudpickle for arbitrary Python objects, with numpy
(and jax-on-host) array payloads carried out-of-band via pickle protocol 5 so
they land in / are read from shared memory without copies.

Store layout for a sealed object::

    u32 magic | u32 n_buffers | u64 pickle_len | n*u64 buffer_lens
    | pickle bytes | pad to 64 | buffer0 | pad to 64 | buffer1 | ...

ObjectRefs and ActorHandles embedded inside values are reduced to portable
descriptors and rehydrated against the current worker on load (the hook is
installed by `ray_tpu._private.worker`).
"""

from __future__ import annotations

import pickle
import struct
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import cloudpickle
import numpy as np

_MAGIC = 0x52545055  # "RTPU"
_ALIGN = 64
_HDR = struct.Struct("<II Q")


class SerializedObject:
    """A pickled payload plus out-of-band buffers, ready to write."""

    __slots__ = ("meta", "buffers", "total_size")

    def __init__(self, meta: bytes, buffers: Sequence[memoryview]):
        self.meta = meta
        self.buffers = [b.cast("B") if b.format != "B" or b.ndim != 1 else b
                        for b in map(memoryview, buffers)]
        size = _HDR.size + 8 * len(self.buffers)
        size = _aligned(size + len(meta))
        for b in self.buffers:
            size = _aligned(size + b.nbytes)
        self.total_size = size

    def write_into(self, dest: memoryview) -> None:
        off = _HDR.size + 8 * len(self.buffers)
        _HDR.pack_into(dest, 0, _MAGIC, len(self.buffers), len(self.meta))
        for i, b in enumerate(self.buffers):
            struct.pack_into("<Q", dest, _HDR.size + 8 * i, b.nbytes)
        dest[off:off + len(self.meta)] = self.meta
        off = _aligned(off + len(self.meta))
        for b in self.buffers:
            dest[off:off + b.nbytes] = b
            off = _aligned(off + b.nbytes)

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_into(memoryview(out))
        return bytes(out)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializationContext:
    """Per-worker serializer; custom reducer hooks are pluggable."""

    def __init__(self):
        # type -> reducer(obj) -> (reconstructor, args)
        self._custom_reducers: dict = {}
        # Called after each deserialize with [(oid, owner_addr)] of refs
        # rehydrated from the payload whose owner is another process —
        # the worker registers these as borrows.
        self._on_deserialize: List[Callable[[Any], None]] = []
        # Per-thread hand-off of refs embedded in the latest serialize():
        # serialize runs concurrently on executor/actor/driver threads, so
        # this must never be shared mutable state.
        self._tls = threading.local()

    @property
    def last_contained_refs(self) -> List:
        return getattr(self._tls, "contained", [])

    def register_reducer(self, type_: type, reducer: Callable) -> None:
        self._custom_reducers[type_] = reducer

    def serialize(self, value: Any) -> SerializedObject:
        buffers: List[pickle.PickleBuffer] = []

        class _Pickler(cloudpickle.Pickler):
            dispatch_table = dict(getattr(cloudpickle.Pickler, "dispatch_table", {}))

        for type_, reducer in self._custom_reducers.items():
            _Pickler.dispatch_table[type_] = reducer

        import io

        from ray_tpu._private import object_ref as _oref

        sink = io.BytesIO()
        pickler = _Pickler(sink, protocol=5, buffer_callback=buffers.append)
        _oref.begin_serialize_capture()
        try:
            pickler.dump(value)
        finally:
            # Refs embedded in the value, for the borrower protocol: the
            # caller decides whether they become object-keyed holders
            # (stored values) or stay covered by task-dep pins (args).
            self._tls.contained = _oref.end_serialize_capture()
        views = [b.raw() for b in buffers]
        return SerializedObject(sink.getvalue(), views)

    def deserialize(self, data: memoryview, keepalive: Any = None) -> Any:
        data = memoryview(data)
        magic, n_buffers, meta_len = _HDR.unpack_from(data, 0)
        if magic != _MAGIC:
            raise ValueError("corrupt object payload (bad magic)")
        sizes = [
            struct.unpack_from("<Q", data, _HDR.size + 8 * i)[0]
            for i in range(n_buffers)
        ]
        off = _HDR.size + 8 * n_buffers
        meta = bytes(data[off:off + meta_len])
        off = _aligned(off + meta_len)
        bufs = []
        for size in sizes:
            view = data[off:off + size]
            if keepalive is not None:
                view = _keepalive_buffer(view, keepalive)
            bufs.append(view)
            off = _aligned(off + size)
        from ray_tpu._private import object_ref as _oref

        _oref.begin_deserialize_capture()
        try:
            value = pickle.loads(meta, buffers=bufs)
        finally:
            borrowed = _oref.end_deserialize_capture()
        # Register in-bound borrows with their owners BEFORE the value is
        # usable: for task args the caller still holds the task-dep pin,
        # so the registration can never race the owner's free.
        for hook in self._on_deserialize:
            hook(borrowed)
        return value


class _KeepaliveArray(np.ndarray):
    """uint8 view of a store buffer that pins the backing mapping.

    pickle's out-of-band loads do ``memoryview(buffer)`` internally, so
    the buffer must support the C buffer protocol — a pure-Python proxy
    (PEP 688 ``__buffer__``) only exists from 3.12. An ndarray subclass
    exports the protocol natively on every version, values rebuilt from
    the buffer keep it alive through ``.base``, and the extra attribute
    keeps the MappedObject (the raylet reader ref) alive with it."""

    _keepalive: Any = None


def _keepalive_buffer(view: memoryview, keepalive: Any) -> np.ndarray:
    arr = np.frombuffer(view, np.uint8).view(_KeepaliveArray)
    arr._keepalive = keepalive
    return arr


def serialize_error(exc: BaseException) -> bytes:
    """Best-effort pickling of an exception for cross-process propagation."""
    import traceback

    try:
        return cloudpickle.dumps((exc, traceback.format_exc()))
    except Exception:
        return cloudpickle.dumps(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), traceback.format_exc())
        )


def deserialize_error(payload: bytes) -> Tuple[BaseException, str]:
    return cloudpickle.loads(payload)
