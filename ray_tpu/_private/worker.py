"""Core worker — the in-process runtime of every driver and worker.

Role-equivalent to the reference's `src/ray/core_worker/` + the Python side of
`_private/worker.py`: object put/get/wait over a two-tier store (in-process
memory store for small/inlined objects — `memory_store.h:43` — and the node's
shared-memory store), task submission over the raylet lease protocol with
spillback (`direct_task_transport.h:75`), direct ordered actor transport with
per-caller sequence numbers (`direct_actor_task_submitter.h`,
`actor_scheduling_queue.h`), owner-side retries (`task_manager.cc:896`), and
the task execution loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import sys
import threading
import time
from collections import defaultdict, deque
from functools import partial
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import (
    ActorID, JobID, ObjectID, TaskID, WorkerID, _IndexCounter,
)
from ray_tpu._private.object_ref import ObjectRef, reduce_object_ref
from ray_tpu._private.object_store import MappedObject, WritableObject
from ray_tpu._private.reference_count import ReferenceCounter
from ray_tpu._private.resources import ResourceSet, TPU
from ray_tpu._private.rpc import (ConnectionLost, RpcClient, RpcServer,
                                  get_io_loop, spawn_task)
from ray_tpu._private.serialization import (
    SerializationContext, SerializedObject, deserialize_error, serialize_error,
)
from ray_tpu._private.task_spec import (
    ArgSpec, FunctionDescriptor, SchedulingStrategySpec, TaskSpec, TaskType,
)

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

_global_worker: Optional["Worker"] = None
_global_lock = threading.Lock()


def global_worker() -> "Worker":
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first.")
    return _global_worker


def global_worker_or_none() -> Optional["Worker"]:
    return _global_worker


def set_global_worker(w: Optional["Worker"]) -> None:
    global _global_worker
    _global_worker = w


def _current_wire_trace() -> Optional[Dict[str, Any]]:
    """The caller's active TraceContext as a compact wire dict for the
    TaskSpec (None when no trace is active) — the submit side of
    request-scoped trace propagation (util/tracing.py)."""
    from ray_tpu.util.tracing import current_wire_context

    return current_wire_context()


class _PendingObject:
    """Memory-store entry: resolves to inline bytes, a plasma copy, or error."""

    __slots__ = ("event", "inline", "error", "in_plasma", "waiters")

    def __init__(self):
        self.event = threading.Event()
        self.inline: Optional[bytes] = None
        self.error: Optional[bytes] = None
        self.in_plasma = False
        self.waiters: List[asyncio.Future] = []


class _GeneratorState:
    """Owner-side progress of one streaming/dynamic generator task."""

    __slots__ = ("produced", "total", "error", "cond")

    def __init__(self):
        self.produced = 0               # item refs completed so far
        self.total: Optional[int] = None  # set when the generator finishes
        self.error: Optional[bytes] = None
        self.cond = threading.Condition()


class _ActorState:
    """Executing-side actor state (instance + ordered scheduling queues)."""

    def __init__(self, instance, spec: TaskSpec):
        self.instance = instance
        self.spec = spec
        self.max_concurrency = max(1, spec.max_concurrency)
        self.is_async = spec.is_async_actor
        self.executors: Dict[str, ThreadPoolExecutor] = {}
        if not self.is_async:
            self.executors[""] = ThreadPoolExecutor(
                max_workers=self.max_concurrency,
                thread_name_prefix="actor-exec")
        self.semaphore = asyncio.Semaphore(self.max_concurrency)
        # per-caller ordering
        self.expected_seq: Dict[bytes, int] = defaultdict(int)
        self.pending: Dict[bytes, Dict[int, asyncio.Future]] = defaultdict(dict)

    def executor_for(self, group: str) -> ThreadPoolExecutor:
        if group not in self.executors:
            self.executors[group] = ThreadPoolExecutor(
                max_workers=max(1, self.max_concurrency),
                thread_name_prefix=f"actor-cg-{group}")
        return self.executors[group]


class ActorHandleTracker:
    """Owner-side actor handle GC (reference: actors die when all handles go
    out of scope, AFTER their outstanding tasks drain). Serialized handles
    conservatively pin the actor.

    All state mutation runs on the io event loop — finalizers (`__del__`)
    must not take locks, since cyclic GC can fire them on a thread already
    inside this tracker.
    """

    def __init__(self, worker: "Worker"):
        self._worker = worker
        self._counts: Dict[bytes, int] = defaultdict(int)
        self._inflight: Dict[bytes, int] = defaultdict(int)
        self._shared: set = set()
        self._created_by_us: set = set()
        self._kill_when_drained: set = set()

    def _post(self, fn) -> None:
        if not self._worker._dead:
            try:
                self._worker.io.loop.call_soon_threadsafe(fn)
            except Exception:
                pass

    def mark_created(self, actor_id: bytes) -> None:
        self._post(lambda: self._created_by_us.add(actor_id))

    def mark_shared(self, actor_id: bytes) -> None:
        self._post(lambda: self._shared.add(actor_id))

    def add_ref(self, actor_id: bytes) -> None:
        self._post(lambda: self._counts.__setitem__(
            actor_id, self._counts[actor_id] + 1))

    def remove_ref(self, actor_id: bytes) -> None:
        """GC-context entry (ActorHandle.__del__): append-only.

        `_post`/call_soon_threadsafe takes the event loop's internal
        mutex — if cyclic GC fires this __del__ on the io-loop thread
        while it is INSIDE call_soon_threadsafe, re-taking that mutex
        self-deadlocks (same class as the ObjectRef.__del__ hang). The
        worker's release drainer applies the decrefs."""
        self._worker._pending_actor_releases.append(actor_id)

    def apply_deferred_release(self, actor_id: bytes) -> None:
        """Drain-point counterpart of remove_ref (non-GC context)."""
        def _dec():
            self._counts[actor_id] -= 1
            self._maybe_gc(actor_id)

        self._post(_dec)

    # Called from the io loop only (submit/complete paths).
    def task_submitted(self, actor_id: bytes) -> None:
        self._inflight[actor_id] += 1

    def task_completed(self, actor_id: bytes) -> None:
        self._inflight[actor_id] -= 1
        if actor_id in self._kill_when_drained:
            self._maybe_gc(actor_id)

    def _maybe_gc(self, actor_id: bytes) -> None:
        if (self._counts[actor_id] > 0
                or actor_id not in self._created_by_us
                or actor_id in self._shared):
            return
        if self._inflight[actor_id] > 0:
            # Reference semantics: let submitted work finish first.
            self._kill_when_drained.add(actor_id)
            return
        self._created_by_us.discard(actor_id)
        self._kill_when_drained.discard(actor_id)
        if not self._worker._dead:
            try:
                self._worker.io.submit(self._worker.gcs.acall(
                    "gc_actor", actor_id=actor_id, timeout=10))
            except Exception:
                pass


class _ActorAddrUnavailable(Exception):
    """The actor has no live address (dead / never became ready)."""


class _LeaseState:
    """Per-scheduling-shape lease bookkeeping on the owner."""

    __slots__ = ("idle", "waiters", "inflight", "event",
                 "dispatcher_started", "pushing", "remote_pending")

    def __init__(self):
        self.idle: deque = deque()      # parked reusable leases
        self.waiters: deque = deque()   # (spec, future) awaiting dispatch
        self.inflight = 0               # raylet lease requests in flight
        self.event = asyncio.Event()    # wakes the dispatcher
        self.dispatcher_started = False
        self.pushing = 0                # batch pushes currently in flight
        # Lease requests currently parked at a *remote* raylet (after a
        # spillback). Each one is an expected grant on an other-node worker;
        # the dispatcher must not starve those nodes by reusing a local
        # finished lease for the waiter the remote grant is coming for
        # (reference contract: the leased-worker cache never starves an
        # idle node — `direct_task_transport.cc:600`).
        self.remote_pending = 0


class _WorkerCrashed:
    """Dispatch outcome: the pushed-to worker died mid-task."""

    __slots__ = ("worker_id", "lessor")

    def __init__(self, worker_id, lessor):
        self.worker_id = worker_id
        self.lessor = lessor


_CANCELLED_SENTINEL = object()


class _ActorSendQueue:
    __slots__ = ("queue", "event", "task")

    def __init__(self):
        self.queue: deque = deque()
        self.event = asyncio.Event()
        self.task = None


class _TaskContext(threading.local):
    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.task_name: str = ""
        self.tpu_ids: List[int] = []


class Worker:
    def __init__(self, mode: str, gcs_addr: Tuple[str, int],
                 raylet_addr: Tuple[str, int], node_id: bytes,
                 job_id: JobID, worker_id: Optional[WorkerID] = None,
                 session_dir: str = ""):
        self.mode = mode
        self.node_id = node_id
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.session_dir = session_dir
        self.io = get_io_loop()

        self.gcs = RpcClient(*gcs_addr)
        self.gcs_addr = gcs_addr
        self.raylet = RpcClient(*raylet_addr)
        self.raylet_addr = raylet_addr

        # Core worker RPC service (worker<->worker plane). Bind the node's
        # routable interface (exported by the raylet) so two physical hosts
        # can exchange owner RPCs and object pulls; loopback only when
        # standalone.
        bind_host = os.environ.get("RAY_TPU_NODE_IP") or raylet_addr[0]
        self.server = RpcServer(bind_host, 0)
        for name in ["push_task", "push_tasks", "create_actor",
                     "push_actor_task", "push_actor_tasks",
                     "get_object_status", "kill_self", "cancel_task", "ping",
                     "busy_info", "add_borrower", "release_borrower",
                     "consume_pending_share",
                     "stack_dump", "dump_stacks", "profile", "tpu_profile",
                     "delete_object_notification", "report_generator_item",
                     "recover_object", "wait_object_status",
                     "early_task_result"]:
            self.server.register(name, getattr(self, f"_h_{name}"))
        self.port = self.server.start()
        self.addr = (bind_host, self.port)

        # serialization
        self.serialization = SerializationContext()
        self.serialization.register_reducer(ObjectRef, reduce_object_ref)
        from ray_tpu.actor import ActorHandle, reduce_actor_handle

        self.serialization.register_reducer(ActorHandle, reduce_actor_handle)

        # object state
        self.reference_counter = ReferenceCounter(
            on_free=self._free_object,
            on_borrow_release=self._send_borrow_release,
            on_contained_free=self._release_contained)
        # oids this process has announced itself as borrowing (dedupes the
        # per-deserialize registration RPC; cleared on release).
        self._borrow_registered: Set[bytes] = set()
        self.serialization._on_deserialize.append(self._register_borrows)
        # _dead must exist before the sweeper's first loop check — the io
        # loop thread is already running and can win the race against the
        # rest of __init__.
        self._dead = False
        self.io.submit(self._borrow_sweeper())
        self.actor_handles = ActorHandleTracker(self)
        self._objects: Dict[bytes, _PendingObject] = {}
        self._objects_lock = threading.Lock()
        # Deferred ref releases from ObjectRef.__del__. A __del__ can run
        # inside ANY allocation on ANY thread — including one already
        # holding _objects_lock (e.g. _entry building a _PendingObject) —
        # so it must never call into the refcounter/free path directly:
        # remove_local_ref -> _free_object re-takes _objects_lock and
        # self-deadlocks while holding the refcount lock, wedging every
        # other thread (observed as the serve-suite hang). __del__ only
        # appends here (GIL-atomic); drains run at public entry points
        # and from the release-drainer io task. Reference analogue:
        # core_worker defers Python refcount ops onto the io_service.
        import collections as _collections

        self._pending_releases: "_collections.deque[bytes]" = \
            _collections.deque()
        # Same contract for MappedObject view releases (raylet client-ref
        # drops) and ActorHandle.__del__ decrefs: GC-time callbacks
        # append; the drainer applies them.
        self._pending_map_releases: "_collections.deque[bytes]" = \
            _collections.deque()
        self._pending_actor_releases: "_collections.deque[bytes]" = \
            _collections.deque()
        self.io.submit(self._release_drainer())
        # Weak cache of client mappings: entries vanish when the last
        # deserialized value sharing the buffer dies, firing the
        # mapping's release callback so the raylet drops its client ref
        # (plasma buffer-release semantics — a strong cache kept every
        # read object reader-pinned forever and wedged small arenas).
        import weakref

        self._mapped: "weakref.WeakValueDictionary[bytes, MappedObject]" = \
            weakref.WeakValueDictionary()

        # counters
        self._put_counter = _IndexCounter()
        self._task_counter = _IndexCounter()
        self._put_inflight = threading.BoundedSemaphore(
            GlobalConfig.async_put_max_inflight)
        self._pending_deletes: Dict[bytes, List[bytes]] = {}
        self._pending_deletes_lock = threading.Lock()
        self._delete_flusher_started = False

        # submission state
        self._worker_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._raylet_clients: Dict[Tuple[str, int], RpcClient] = {self.raylet_addr: self.raylet}
        self._actor_addr_cache: Dict[bytes, Tuple[str, int]] = {}
        self._actor_seq: Dict[bytes, int] = defaultdict(int)
        self._actor_incarnation: Dict[bytes, int] = {}
        self._actor_submit_locks: Dict[bytes, asyncio.Lock] = {}
        self._actor_batchers: Dict[bytes, "_ActorSendQueue"] = {}
        self._exported_functions: set = set()
        self._prepared_env_cache: Dict[str, Dict[str, Any]] = {}
        self._exported_payloads: Dict[str, bytes] = {}
        self._cancelled_tasks: set = set()
        # task_id -> executing worker addr, while a push RPC is in flight
        # (real cancel needs the executing worker, not a broadcast).
        self._inflight_push: Dict[bytes, Tuple[str, int]] = {}
        # Dispatch futures for multi-task push batches, keyed by task id —
        # the early_task_result side channel resolves them before the
        # aggregate batch reply lands (anti-deadlock; see _h_push_tasks).
        self._inflight_futs: Dict[bytes, Any] = {}
        # Leased-worker reuse (reference: direct task submitter lease
        # caching in `lease_policy.h` / `normal_task_submitter`): a lease
        # whose task finished cleanly is handed to the next same-shaped
        # waiting task (or parked briefly) without another raylet round
        # trip. A sweeper returns leases idle too long.
        self._lease_pool: Dict[str, _LeaseState] = {}
        self._lease_pool_sweeper_started = False
        # fn hash -> EMA of worker-measured execution seconds, for the
        # batch-or-not dispatch decision.
        self._fn_dur_ema: Dict[str, float] = {}
        # Streaming/dynamic generator tasks: task_id -> production state.
        self._generators: Dict[bytes, _GeneratorState] = {}
        # Lineage (object reconstruction): task_id -> spec of the creating
        # task, dropped when all its return objects are freed
        # (reference: `task_manager.cc` lineage + `object_recovery_manager.h:90`).
        self._lineage: Dict[bytes, TaskSpec] = {}
        self._lineage_live: Dict[bytes, int] = {}
        self._recovering: Dict[bytes, threading.Event] = {}
        # Task lifecycle events, flushed to the GCS task manager in batches
        # (reference: `task_event_buffer.h:206` -> `gcs_task_manager.h:85`).
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_lock = threading.Lock()
        self._task_events_flush_pending = False

        # execution state
        self._fn_cache: Dict[str, Any] = {}
        self._task_executor = ThreadPoolExecutor(
            max_workers=max(4, (os.cpu_count() or 4)),
            thread_name_prefix="task-exec")
        self._actor: Optional[_ActorState] = None
        self._ctx = _TaskContext()
        self._running_task_threads: Dict[bytes, threading.Thread] = {}
        # task_id -> thread ident, for async cancel of a RUNNING task,
        # plus the inverse so cancel can verify the thread still runs THAT
        # task before injecting (thread reuse race).
        self._executing_tids: Dict[bytes, int] = {}
        self._thread_task: Dict[int, bytes] = {}

        self._dead = False

        self.gcs.call("register_worker", worker_id=self.worker_id.binary(),
                      info={"worker_id": self.worker_id.binary(),
                            "node_id": node_id, "mode": mode,
                            "addr": self.addr, "pid": os.getpid(),
                            "job_id": job_id.binary()})

        async def _task_event_flusher():
            while not self._dead:
                await asyncio.sleep(2.0)
                self.flush_task_events()

        self.io.submit(_task_event_flusher())

    # ======================================================================
    # Object plane
    # ======================================================================
    def _entry(self, oid: bytes, create: bool = True) -> Optional[_PendingObject]:
        with self._objects_lock:
            entry = self._objects.get(oid)
            if entry is None and create:
                entry = self._objects[oid] = _PendingObject()
            return entry

    def _complete_object(self, oid: bytes, *, inline: Optional[bytes] = None,
                         error: Optional[bytes] = None,
                         in_plasma: bool = False) -> None:
        entry = self._entry(oid)
        entry.inline = inline
        entry.error = error
        entry.in_plasma = in_plasma
        entry.event.set()
        if entry.waiters:
            waiters, entry.waiters = entry.waiters, []

            def _wake():
                for f in waiters:
                    if not f.done():
                        f.set_result(None)

            self.io.loop.call_soon_threadsafe(_wake)

    async def _await_entry(self, oid: bytes, timeout: Optional[float]) -> bool:
        entry = self._entry(oid)
        if entry.event.is_set():
            return True
        fut = asyncio.get_running_loop().create_future()
        entry.waiters.append(fut)
        if entry.event.is_set() and not fut.done():
            fut.set_result(None)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def put(self, value: Any) -> ObjectRef:
        self.drain_releases()
        task_id = self._ctx.task_id or TaskID.for_normal_task(self.job_id)
        oid_obj = ObjectID.for_put(task_id, self._put_counter.next())
        oid = oid_obj.binary()
        self.reference_counter.add_owned(oid)
        self._store_value(oid, value)
        return ObjectRef(oid, self.addr, self.worker_id.binary())

    def _store_value(self, oid: bytes, value: Any) -> None:
        sobj = self.serialization.serialize(value)
        # Refs nested in the stored value stay alive while this object
        # does (object-keyed borrow; reference: nested refs in
        # reference_count.cc).
        self._adopt_contained(oid, self.serialization.last_contained_refs)
        if sobj.total_size <= GlobalConfig.max_direct_call_object_size:
            self._complete_object(oid, inline=sobj.to_bytes())
        elif sobj.total_size <= GlobalConfig.rpc_put_max_bytes:
            # Pipelined single-RPC put: the staging copy decouples the
            # object from later caller-side mutation, then the whole
            # create+write+seal happens in one raylet round trip that the
            # caller never waits on (ray.get blocks on the entry instead).
            self._async_plasma_put(oid, sobj.to_bytes())
        else:
            self._plasma_put(oid, sobj)
            self.reference_counter.add_location(oid, self.node_id)
            self._complete_object(oid, in_plasma=True)

    def _async_plasma_put(self, oid: bytes, payload: bytes) -> None:
        self._put_inflight.acquire()

        async def _chain():
            try:
                await self.raylet.acall(
                    "put_object", object_id=oid, payload=payload, pin=True,
                    timeout=60)
                if self.reference_counter.is_freed(oid):
                    # Every ref was dropped while the put was in flight:
                    # nobody will ever decref again, so delete the pinned
                    # copy now or it leaks in the arena forever.
                    await self.raylet.acall("delete_objects",
                                            object_ids=[oid], timeout=10)
                    return
                self.reference_counter.add_location(oid, self.node_id)
                self._complete_object(oid, in_plasma=True)
            except Exception as e:  # noqa: BLE001 — surfaces at get()
                self._complete_object(oid, error=serialize_error(e))
            finally:
                self._put_inflight.release()

        try:
            self.io.submit(_chain())
        except Exception:
            self._put_inflight.release()
            raise

    def _plasma_put(self, oid: bytes, sobj: SerializedObject) -> None:
        reply = self.raylet.call("create_object", object_id=oid,
                                 size=sobj.total_size)
        wobj = WritableObject(reply["path"], sobj.total_size,
                              reply.get("offset", 0))
        try:
            sobj.write_into(wobj.view)
        finally:
            wobj.close()
        self.raylet.call("seal_object", object_id=oid, pin=True)

    def _release_mapping(self, oid: bytes) -> None:
        """MappedObject release callback: the last value view died.

        Usually fires from GC (the WeakValueDictionary entry dying), so
        it must stay lock-free like ObjectRef.__del__ — io.submit takes
        the asyncio loop's internal mutex and can self-deadlock if the
        collection happens inside call_soon_threadsafe on the loop
        thread. Defer; the drainer sends the raylet release."""
        if self._dead:
            return
        self._pending_map_releases.append(oid)

    def _plasma_get(self, oid: bytes, timeout: Optional[float],
                    locations: Sequence[bytes]) -> Any:
        mobj = self._mapped.get(oid)
        if mobj is None:
            reply = self.raylet.call("get_object", object_id=oid,
                                     wait_timeout=timeout,
                                     locations=list(locations),
                                     client_id=self.worker_id.binary())
            if reply.get("not_found"):
                raise exc.ObjectLostError(
                    f"object {oid.hex()} not found in the cluster")
            mobj = MappedObject(reply["path"], reply["size"],
                                reply.get("offset", 0),
                                on_release=partial(
                                    self._release_mapping, oid))
            self._mapped[oid] = mobj
        return self.serialization.deserialize(mobj.view, keepalive=mobj)

    def get_objects(self, refs: Sequence[ObjectRef],
                    timeout: Optional[float]) -> List[Any]:
        self.drain_releases()
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            out.append(self._get_one(ref, remaining))
        return out

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        oid = ref.binary()
        entry = self._entry(oid, create=False)
        owned = entry is not None or ref.owner_addr == self.addr
        if owned:
            if self.reference_counter.is_freed(oid):
                raise exc.ObjectLostError(
                    f"object {oid.hex()} was already freed by its owner")
            entry = self._entry(oid)
            if not self._wait_entry(entry, timeout, oid):
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid.hex()}")
            return self._materialize(oid, entry, timeout)
        return self._borrowed_get(ref, timeout)

    def _wait_entry(self, entry, timeout: Optional[float],
                    oid: bytes) -> bool:
        """Event-wait in slices so a get() can notice that the runtime it
        is waiting on has died (worker shutdown, io loop gone) instead of
        sleeping out its entire — possibly 600 s — budget on an object
        that can no longer arrive. Emits a progress diagnostic every
        couple of minutes so a wedged suite run leaves a trail."""
        deadline = None if timeout is None else time.monotonic() + timeout
        waited = 0.0
        while True:
            # Already-resolved objects succeed even at timeout=0 — a
            # zero budget means "don't block", not "don't look".
            if entry.event.is_set():
                return True
            slice_s = 30.0
            if deadline is not None:
                slice_s = min(slice_s, deadline - time.monotonic())
                if slice_s <= 0:
                    return False
            if entry.event.wait(slice_s):
                return True
            waited += slice_s
            if self._dead:
                raise exc.RaySystemError(
                    f"worker shut down while waiting for {oid.hex()}")
            if not self.io._thread.is_alive():
                raise exc.RaySystemError(
                    f"io loop died while waiting for {oid.hex()}")
            if waited >= 120 and int(waited) % 120 < 30:
                print(f"[worker] still waiting for {oid.hex()} after "
                      f"{waited:.0f}s (task dispatch pending)",
                      file=sys.stderr, flush=True)

    def _materialize(self, oid: bytes, entry: _PendingObject,
                     timeout: Optional[float], _recovered: bool = False) -> Any:
        if entry.error is not None:
            self._raise_task_error(entry.error)
        if entry.inline is not None:
            return self.serialization.deserialize(memoryview(entry.inline))
        if entry.in_plasma:
            try:
                return self._plasma_get(
                    oid, timeout, self.reference_counter.locations(oid))
            except exc.ObjectLostError:
                if _recovered or not self._try_recover_object(oid, timeout):
                    raise
                entry = self._entry(oid)
                if not entry.event.wait(timeout if timeout is not None
                                        else 300):
                    raise
                return self._materialize(oid, entry, timeout,
                                         _recovered=True)
        raise exc.ObjectLostError(f"object {oid.hex()} has no value")

    def _raise_task_error(self, payload: bytes):
        cause, tb = deserialize_error(payload)
        if isinstance(cause, exc.RayTpuError) and not isinstance(
                cause, exc.RayTaskError):
            raise cause
        raise exc.RayTaskError(cause, tb).as_instanceof_cause()

    def _borrowed_get(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        oid = ref.binary()
        deadline = None if timeout is None else time.monotonic() + timeout
        owner = self._client_for(tuple(ref.owner_addr))
        recovery_attempts = 0
        first = True
        while True:
            try:
                if first:
                    # Fast path: object usually already resolved.
                    status = owner.call("get_object_status", object_id=oid,
                                        timeout=30)
                    first = False
                else:
                    window = 10.0
                    if deadline is not None:
                        window = max(0.05, min(
                            window, deadline - time.monotonic()))
                    status = owner.call("wait_object_status", object_id=oid,
                                        wait_timeout=window,
                                        timeout=window + 30)
            except (ConnectionLost, OSError):
                raise exc.OwnerDiedError(
                    f"owner of {oid.hex()} at {ref.owner_addr} is unreachable; "
                    "the object is lost") from None
            kind = status.get("status")
            if kind == "inline":
                return self.serialization.deserialize(
                    memoryview(status["data"]))
            if kind == "plasma":
                try:
                    return self._plasma_get(
                        oid,
                        None if deadline is None else max(
                            0.1, deadline - time.monotonic()),
                        status["locations"])
                except exc.ObjectLostError:
                    # All copies gone — ask the owner to reconstruct via
                    # lineage, then re-resolve. Bounded by the caller's
                    # remaining get() budget.
                    recovery_attempts += 1
                    if recovery_attempts > 2:
                        raise
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise exc.GetTimeoutError(
                            f"get() timed out during recovery of "
                            f"{oid.hex()}") from None
                    reply = owner.call(
                        "recover_object", object_id=oid,
                        timeout=(310 if remaining is None
                                 else min(remaining + 10, 310)))
                    if not reply.get("ok"):
                        raise
                    continue
            if kind == "error":
                self._raise_task_error(status["error"])
            if kind == "freed":
                raise exc.ObjectLostError(
                    f"object {oid.hex()} was freed by its owner")
            if deadline is not None and time.monotonic() > deadline:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for borrowed {oid.hex()}")

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        refs = list(refs)
        ready_ids: set = set()   # sticky: a ready object stays ready
        delay = 0.002
        while True:
            ready, not_ready = [], []
            for ref in refs:
                if ref.binary() in ready_ids or self._is_ready(ref):
                    ready_ids.add(ref.binary())
                    ready.append(ref)
                else:
                    not_ready.append(ref)
            if len(ready) >= num_returns or (
                    deadline is not None and time.monotonic() >= deadline):
                # Reference semantics: at most num_returns refs are reported
                # ready; the surplus stays in the not-ready list, in order.
                capped = ready[:num_returns]
                capped_ids = {id(r) for r in capped}
                rest = [r for r in refs if id(r) not in capped_ids]
                return capped, rest
            time.sleep(delay)
            delay = min(delay * 1.5, 0.05)

    def _is_ready(self, ref: ObjectRef) -> bool:
        entry = self._entry(ref.binary(), create=False)
        if entry is not None:
            return entry.event.is_set()
        if ref.owner_addr == self.addr:
            return False
        try:
            status = self._client_for(tuple(ref.owner_addr)).call(
                "get_object_status", object_id=ref.binary(), timeout=10)
            return status.get("status") != "pending"
        except Exception:
            return True  # owner dead => get() will raise; counts as "ready"

    def _free_object(self, oid: bytes, locations: set) -> None:
        """ReferenceCounter callback — remove the value everywhere."""
        with self._objects_lock:
            self._objects.pop(oid, None)

        tid = bytes(oid[:TaskID.SIZE])
        live = self._lineage_live.get(tid)
        if live is not None:
            live -= 1
            if live <= 0:
                self._drop_lineage(tid)
            else:
                self._lineage_live[tid] = live
        mobj = self._mapped.pop(oid, None)
        if mobj is not None:
            mobj.close()  # fires the release callback exactly once
        if self._dead:
            return
        if not locations and mobj is None:
            # Inline-only object: nothing lives in any node store — a
            # delete RPC per freed ref would dominate small-task GC.
            return
        # Batched store deletion: freed plasma objects accumulate and one
        # delete_objects RPC per node flushes them (500 puts freed at once
        # previously spawned 500 RPC chains).
        with self._pending_deletes_lock:
            for node in locations | {self.node_id}:
                self._pending_deletes.setdefault(node, []).append(oid)
            start = not self._delete_flusher_started
            self._delete_flusher_started = True
        if start:
            try:
                self.io.submit(self._delete_flusher())
            except Exception:
                pass

    # ---- borrower protocol (reference: reference_count.cc borrowed refs,
    # WaitForRefRemoved; here: explicit register/release RPCs + TTL'd
    # pending-share pins + owner-side borrower liveness sweep) ------------

    def _register_borrows(self, borrowed) -> None:
        """Deserialize hook: we just rehydrated refs owned elsewhere —
        announce the borrow to each owner before the value is usable."""
        if not borrowed or self._dead:
            return
        for oid, owner_addr in borrowed:
            if oid in self._borrow_registered:
                # Already a registered borrower: this extra copy's
                # serialize-out still appended a pending share owner-side
                # that nothing would ever consume (it would pin the object
                # for the full TTL — ADVICE r4 low). Retire it now; the
                # registered borrow itself keeps the object alive.
                self._consume_share_async(oid, owner_addr)
                continue
            # Optimistic dedupe entry (prevents duplicate RPCs from rapid
            # repeated deserializes); rolled back on failure so the next
            # deserialize retries the registration.
            self._borrow_registered.add(oid)
            try:
                if threading.current_thread() is getattr(
                        self.io, "_thread", None):
                    # On the io loop itself a sync RPC would deadlock;
                    # fire async — the serializer's pending-share pin (or
                    # the caller's task-dep pin) covers the gap.
                    self.io.submit(self._register_borrow_async(
                        oid, owner_addr))
                else:
                    self._client_for(owner_addr).call(
                        "add_borrower", object_id=oid,
                        key=self.worker_id.binary(),
                        addr=list(self.addr), timeout=30)
            except Exception:
                # Owner unreachable NOW: drop the dedupe entry so a later
                # deserialize retries; until then the ref may dangle and
                # get() surfaces ObjectLostError.
                self._borrow_registered.discard(oid)

    def _consume_share_async(self, oid: bytes, owner_addr) -> None:
        """Best-effort, fire-and-forget: tell the owner one in-flight
        pending share was delivered to an already-registered borrower.
        Never retried (shares are fungible; an over-consume could drop
        the pin covering a different in-flight copy), so a lost message
        just falls back to the TTL sweep."""
        if self._dead or owner_addr is None:
            return

        async def _go():
            try:
                await self._client_for(tuple(owner_addr)).acall(
                    "consume_pending_share", object_id=oid, timeout=30)
            except Exception:
                pass

        try:
            self.io.submit(_go())
        except Exception:
            pass

    async def _h_consume_pending_share(self, object_id):
        self.reference_counter.consume_pending_share(object_id)
        return True

    async def _register_borrow_async(self, oid: bytes, owner_addr) -> None:
        try:
            await self._client_for(owner_addr).acall(
                "add_borrower", object_id=oid,
                key=self.worker_id.binary(),
                addr=list(self.addr), timeout=30)
        except Exception:
            self._borrow_registered.discard(oid)

    def _send_borrow_release(self, oid: bytes, addr) -> None:
        """ReferenceCounter callback (borrower side): our last hold on a
        borrowed ref drained."""
        self._borrow_registered.discard(oid)
        if self._dead:
            return

        async def _go():
            try:
                await self._client_for(tuple(addr)).acall(
                    "release_borrower", object_id=oid,
                    key=self.worker_id.binary(), timeout=30)
            except Exception:
                pass

        try:
            self.io.submit(_go())
        except Exception:
            pass

    def _release_contained(self, outer: bytes, inners) -> None:
        """ReferenceCounter callback (owner side): a freed object's value
        embedded other refs — drop the object-keyed holds."""
        key = b"obj:" + outer
        for inner, iaddr in inners:
            if iaddr is None or tuple(iaddr) == self.addr:
                self.reference_counter.release_borrower(inner, key)
            elif not self._dead:
                async def _go(a=tuple(iaddr), i=inner):
                    try:
                        await self._client_for(a).acall(
                            "release_borrower", object_id=i, key=key,
                            timeout=30)
                    except Exception:
                        pass

                try:
                    self.io.submit(_go())
                except Exception:
                    pass

    def _adopt_contained(self, outer: bytes, inners) -> None:
        """We own `outer`, whose sealed value embeds `inners`: hold an
        object-keyed borrow on each until `outer` is freed."""
        if not inners:
            return
        key = b"obj:" + outer
        recorded = []
        for inner, iaddr in inners:
            iaddr = tuple(iaddr) if iaddr else None
            if iaddr is None or iaddr == self.addr:
                self.reference_counter.register_borrower(inner, key, None)
                recorded.append((inner, None))
            else:
                client = self._client_for(iaddr)
                try:
                    # Carry OUR address so the inner owner's liveness
                    # sweep can reap the object-keyed hold if this
                    # process dies before freeing `outer`.
                    self.io.submit(client.acall(
                        "add_borrower", object_id=inner, key=key,
                        addr=list(self.addr), timeout=30))
                except Exception:
                    pass
                recorded.append((inner, iaddr))
        self.reference_counter.set_contained(outer, recorded)

    async def _h_add_borrower(self, object_id, key, addr=None):
        return {"ok": self.reference_counter.register_borrower(
            object_id, key, tuple(addr) if addr else None)}

    async def _h_release_borrower(self, object_id, key):
        self.reference_counter.release_borrower(object_id, key)
        return True

    def defer_release(self, oid: bytes) -> None:
        """GC-safe local-ref release (ObjectRef.__del__ only): a single
        lock-free append; the actual decref runs at the next drain."""
        self._pending_releases.append(oid)

    def drain_releases(self) -> None:
        """Apply deferred __del__ releases. Called from public entry
        points (never while holding _objects_lock) and periodically."""
        q = self._pending_releases
        big = len(q) > 100_000
        if big:
            t0 = time.monotonic()
            n0 = len(q)
        while q:
            try:
                oid = q.popleft()
            except IndexError:
                break
            try:
                self.reference_counter.remove_local_ref(oid)
            except Exception:
                pass
        if big:
            print(f"[worker] drained {n0} deferred releases in "
                  f"{time.monotonic() - t0:.2f}s", file=sys.stderr,
                  flush=True)
        aq = self._pending_actor_releases
        while aq:
            try:
                actor_id = aq.popleft()
            except IndexError:
                break
            try:
                self.actor_handles.apply_deferred_release(actor_id)
            except Exception:
                pass
        mq = self._pending_map_releases
        while mq and not self._dead:
            try:
                oid = mq.popleft()
            except IndexError:
                break
            try:
                self.io.submit(self.raylet.acall(
                    "release_object", object_id=oid,
                    client_id=self.worker_id.binary(), timeout=5))
            except Exception:
                pass

    async def _release_drainer(self):
        while not self._dead:
            await asyncio.sleep(0.2)
            if self._pending_releases or self._pending_map_releases:
                self.drain_releases()

    async def _borrow_sweeper(self):
        """Owner-side hygiene: expire unclaimed pending-share pins and
        reap borrowers whose process died without releasing."""
        fails: Dict[Tuple[str, int], int] = {}
        while not self._dead:
            ttl = GlobalConfig.borrow_pending_ttl_s
            await asyncio.sleep(min(30.0, max(0.5, ttl / 4)))
            if self._dead:
                return
            try:
                self.reference_counter.expire_pending(ttl)
                for addr, entries in list(
                        self.reference_counter.borrower_addrs().items()):
                    if addr == self.addr:
                        continue
                    try:
                        await asyncio.wait_for(
                            self._client_for(addr).acall("ping", timeout=5),
                            5)
                        fails.pop(addr, None)
                    except Exception:
                        n = fails.get(addr, 0) + 1
                        fails[addr] = n
                        if n >= 3:
                            fails.pop(addr, None)
                            for oid, bkey in entries:
                                self.reference_counter.release_borrower(
                                    oid, bkey)
            except Exception:
                pass

    async def _delete_flusher(self):
        while not self._dead:
            await asyncio.sleep(0.05)
            with self._pending_deletes_lock:
                batch, self._pending_deletes = self._pending_deletes, {}
            for node, oids in batch.items():
                client = (self.raylet if node == self.node_id
                          else await self._araylet_for_node(node))
                if client is None:
                    continue
                try:
                    await client.acall("delete_objects", object_ids=oids,
                                       timeout=10)
                except Exception:
                    pass

    async def _araylet_for_node(self, node_id: bytes) -> Optional[RpcClient]:
        try:
            nodes = await self.gcs.acall("get_all_nodes", timeout=5)
        except Exception:
            return None
        for n in nodes:
            if n["node_id"] == node_id and n["state"] == "ALIVE":
                return self._raylet_client(tuple(n["addr"]))
        return None

    def _raylet_for_node(self, node_id: bytes) -> Optional[RpcClient]:
        # Resolve a raylet address through GCS (cached by addr).
        try:
            nodes = self.gcs.call("get_all_nodes", timeout=5)
        except Exception:
            return None
        for n in nodes:
            if n["node_id"] == node_id and n["state"] == "ALIVE":
                return self._raylet_client(tuple(n["addr"]))
        return None

    def _raylet_client(self, addr: Tuple[str, int]) -> RpcClient:
        if addr not in self._raylet_clients:
            self._raylet_clients[addr] = RpcClient(*addr)
        return self._raylet_clients[addr]

    def _client_for(self, addr: Tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        if addr not in self._worker_clients:
            self._worker_clients[addr] = RpcClient(*addr)
        return self._worker_clients[addr]

    # ======================================================================
    # Normal task submission (owner side)
    # ======================================================================
    def export_function(self, payload: bytes) -> str:
        fn_hash = hashlib.sha256(payload).hexdigest()[:32]
        if fn_hash not in self._exported_functions:
            self.gcs.call("kv_put", namespace="fn", key=fn_hash,
                          value=payload, overwrite=False)
            self._exported_functions.add(fn_hash)
            # Keep the payload: a bounced GCS may have snapshotted before
            # this export landed, in which case the owner re-exports on
            # the first function-not-found task failure.
            self._exported_payloads[fn_hash] = payload
        return fn_hash

    async def _maybe_reexport(self, fn_hash: str) -> bool:
        payload = self._exported_payloads.get(fn_hash)
        if payload is None:
            return False
        try:
            await self.gcs.acall("kv_put", namespace="fn", key=fn_hash,
                                 value=payload, overwrite=True, timeout=10)
            return True
        except Exception:
            return False

    def _serialize_args(self, args: Sequence[Any], kwargs: Dict[str, Any]
                        ) -> Tuple[List[ArgSpec], List[str]]:
        specs: List[ArgSpec] = []
        all_args = list(args) + list(kwargs.values())
        for value in all_args:
            if isinstance(value, ObjectRef):
                self.reference_counter.add_task_dependency(value.binary())
                specs.append(ArgSpec(
                    is_ref=True, object_id=value.binary(),
                    owner_addr=value.owner_addr))
                continue
            sobj = self.serialization.serialize(value)
            if sobj.total_size <= GlobalConfig.max_direct_call_object_size:
                specs.append(ArgSpec(is_ref=False, inline_data=sobj.to_bytes()))
            else:
                ref = self.put(value)
                self.reference_counter.add_task_dependency(ref.binary())
                specs.append(ArgSpec(is_ref=True, object_id=ref.binary(),
                                     owner_addr=ref.owner_addr))
        return specs, list(kwargs.keys())

    def _prepare_runtime_env(self, env):
        """Driver-side runtime_env normalization + code packaging
        (reference: upload_working_dir_if_needed): validates the spec,
        zips local working_dir / py_modules into content-addressed GCS
        packages, and caches the rewritten env so repeated submissions
        don't re-hash directories."""
        if not env:
            return None
        import json as _json

        key = _json.dumps(env, sort_keys=True, default=str)
        prepared = self._prepared_env_cache.get(key)
        if prepared is None:
            from ray_tpu.runtime_env.manager import prepare_runtime_env

            prepared = prepare_runtime_env(env, self.gcs) or {}
            self._prepared_env_cache[key] = prepared
        return prepared or None

    def submit_task(self, fn_hash: str, fn_name: str, args, kwargs,
                    options: Dict[str, Any]) -> List[ObjectRef]:
        self.drain_releases()
        task_id = TaskID.for_normal_task(self.job_id)
        arg_specs, kw_keys = self._serialize_args(args, kwargs)
        num_returns = options.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if isinstance(num_returns, str):
            num_returns = {"dynamic": -1, "streaming": -2}[num_returns]
        resources = _resources_from_options(options)
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id,
            task_type=TaskType.NORMAL_TASK,
            function=FunctionDescriptor("", fn_name, fn_hash),
            args=arg_specs, kwargs_keys=kw_keys,
            num_returns=num_returns, resources=resources,
            owner_addr=self.addr, owner_worker_id=self.worker_id,
            name=options.get("name") or fn_name,
            scheduling=_strategy_from_options(options),
            max_retries=options.get("max_retries",
                                    GlobalConfig.task_max_retries_default),
            retry_exceptions=options.get("retry_exceptions", False),
            runtime_env=self._prepare_runtime_env(
                options.get("runtime_env")),
            parent_task_id=self._ctx.task_id,
            labels=options.get("_labels") or {},
            trace_ctx=_current_wire_trace(),
        )
        refs = []
        for rid in spec.return_ids():
            self.reference_counter.add_owned(rid.binary())
            self._entry(rid.binary())
            refs.append(ObjectRef(rid.binary(), self.addr,
                                  self.worker_id.binary()))
        if spec.max_retries != 0:
            tid = task_id.binary()
            self._lineage[tid] = spec
            self._lineage_live[tid] = len(refs)
        if num_returns < 0:
            # Register generator state before dispatch: a streaming item
            # push may arrive before the submit coroutine even runs.
            self._generators[task_id.binary()] = _GeneratorState()
        if GlobalConfig.sched_phase_instrumentation:
            # Phase breakdown anchor: the same wall clock goes into the
            # task-event ring and the spec stash, so the histogram and
            # the timeline segments agree to the microsecond.
            spec.phase_ts = {"PENDING": time.time()}
            self._record_task_event(spec, "PENDING",
                                    ts=spec.phase_ts["PENDING"])
        else:
            self._record_task_event(spec, "PENDING")
        self.io.submit(self._run_normal_task(spec))
        if streaming:
            from ray_tpu._private.object_ref import ObjectRefGenerator

            gen = ObjectRefGenerator(task_id.binary(), self.addr,
                                     self.worker_id.binary())
            gen._ref0 = refs[0]  # keeps the generator ref (and lineage) alive
            return [gen]
        return refs

    def _record_task_event(self, spec: TaskSpec, state: str,
                           **extra) -> None:
        event = {
            "task_id": spec.task_id.binary(), "name": spec.name,
            "job_id": spec.job_id.binary(), "state": state,
            "ts": time.time(), "owner_pid": os.getpid(),
            "parent_task_id": (spec.parent_task_id.binary()
                               if spec.parent_task_id else None),
            **extra,
        }
        with self._task_events_lock:
            self._task_events.append(event)
            flush = len(self._task_events) >= 100
        if flush:
            self.flush_task_events()

    def flush_task_events(self) -> None:
        with self._task_events_lock:
            batch, self._task_events = self._task_events, []
        if not batch or self._dead:
            return

        async def _push():
            try:
                await self.gcs.acall("push_task_events", events=batch,
                                     timeout=10)
            except Exception:
                pass

        try:
            self.io.submit(_push())
        except Exception:
            pass

    def flush_task_events_soon(self, delay: float = 0.5) -> None:
        """Debounced flush: schedule one flush ``delay`` seconds out,
        coalescing every request made while it is pending. Trace-tagged
        spans use this so traces assemble at the GCS on a sub-second
        cadence without a per-span RPC (the plain batch flush only
        fires at 100 buffered events or shutdown). Thread-safe —
        ``EventLoopThread.submit`` is."""
        if self._dead:
            return
        with self._task_events_lock:
            if self._task_events_flush_pending:
                return
            self._task_events_flush_pending = True

        async def _later():
            try:
                await asyncio.sleep(delay)
            finally:
                with self._task_events_lock:
                    self._task_events_flush_pending = False
            self.flush_task_events()

        try:
            self.io.submit(_later())
        except Exception:
            with self._task_events_lock:
                self._task_events_flush_pending = False

    def _record_reply_phases(self, spec: TaskSpec,
                             wphases: Dict[str, float],
                             worker_addr) -> None:
        """Owner-side landing of the executing worker's phase clocks
        (WORKER_STARTED / ARGS_READY / RUNNING, stamped worker-side and
        carried in the task reply): append them to the task-event ring
        with their original timestamps — the refined RUNNING supersedes
        the push-time one in the timeline — and fold the full
        PENDING->...->RUNNING chain into rtpu_sched_phase_seconds."""
        from ray_tpu.observability import profiling as _profiling

        for state in ("WORKER_STARTED", "ARGS_READY", "RUNNING"):
            ts = wphases.get(state)
            if ts is None:
                continue
            extra = {"ts": ts}
            if state == "RUNNING":
                extra["worker_addr"] = list(worker_addr)
            self._record_task_event(spec, state, **extra)
        chain = dict(spec.phase_ts or {})
        chain.update(wphases)
        try:
            _profiling.observe_sched_phases(chain)
        except Exception:
            pass  # metrics must never fail a task

    async def _resolve_deps(self, spec: TaskSpec) -> Optional[bytes]:
        """Wait for owned arg refs to be available; returns error payload if a
        dependency failed (which poisons this task)."""
        for arg in spec.args:
            if not arg.is_ref:
                continue
            if tuple(arg.owner_addr) == self.addr:
                await self._await_entry(arg.object_id, None)
                entry = self._entry(arg.object_id)
                if entry.error is not None:
                    return entry.error
            else:
                owner = self._client_for(tuple(arg.owner_addr))
                while True:
                    try:
                        # Long-poll: the owner replies when the object
                        # resolves (or its window closes), instead of the
                        # submitter burning a 10ms poll loop per dep.
                        status = await owner.acall(
                            "wait_object_status", object_id=arg.object_id,
                            wait_timeout=10.0, timeout=40)
                    except (ConnectionLost, OSError):
                        return serialize_error(exc.OwnerDiedError(
                            f"owner of dependency {arg.object_id.hex()} died"))
                    if status.get("status") == "error":
                        return status["error"]
                    if status.get("status") != "pending":
                        break
        return None

    async def _run_normal_task(self, spec: TaskSpec, attempt: int = 0) -> None:
        try:
            await self._run_normal_task_inner(spec, attempt)
        except asyncio.CancelledError:
            # A cancelled dispatcher (io-loop shutdown, or any stray
            # cancellation) previously sailed past `except Exception` and
            # left every return entry unresolved — get() callers then
            # waited out their FULL timeout on an object that could never
            # arrive (the in-suite materialize wedge). Resolve the
            # entries with an error before propagating.
            self._fail_task(spec, serialize_error(exc.RaySystemError(
                f"dispatcher for task {spec.name} was cancelled "
                "(worker shutting down?)")))
            self._release_deps(spec)
            raise
        except Exception as e:  # noqa: BLE001 — submission machinery crashed
            self._fail_task(spec, serialize_error(e))
            # Every failure path must drop the task's pinned dependency
            # refs or repeated failures (e.g. runtime_env setup errors)
            # pin objects in the store forever.
            self._release_deps(spec)

    async def _run_normal_task_inner(self, spec: TaskSpec, attempt: int) -> None:
        dep_error = await self._resolve_deps(spec)
        if dep_error is not None:
            self._fail_task(spec, dep_error)
            self._release_deps(spec)
            return

        reexported = False
        # Memory-monitor preemptions get their own small retry budget:
        # the raylet rescheduled the task on purpose (PREEMPT_RESCHEDULE),
        # so even a max_retries=0 task reruns instead of failing for an
        # infra decision it didn't cause.
        preempt_retries = 0
        while True:
            if spec.task_id.binary() in self._cancelled_tasks:
                self._fail_task(spec, serialize_error(
                    exc.TaskCancelledError(f"task {spec.name} was cancelled")))
                self._release_deps(spec)
                return
            outcome = await self._dispatch_task(spec)
            if outcome is None:
                self._fail_task(spec, serialize_error(exc.RaySystemError(
                    f"could not lease a worker for task {spec.name} "
                    f"(resources {spec.resources.to_dict()} infeasible or "
                    "timeout)")))
                self._release_deps(spec)
                return
            if outcome is _CANCELLED_SENTINEL:
                self._fail_task(spec, serialize_error(
                    exc.TaskCancelledError(f"task {spec.name} was cancelled")))
                self._release_deps(spec)
                return
            if isinstance(outcome, _WorkerCrashed):
                if spec.task_id.binary() in self._cancelled_tasks:
                    # force-cancel kills the executing worker; that death
                    # is the cancellation, not a crash to retry.
                    self._fail_task(spec, serialize_error(
                        exc.TaskCancelledError(
                            f"task {spec.name} was cancelled (force)")))
                    self._release_deps(spec)
                    return
                if attempt < spec.max_retries:
                    attempt += 1
                    self._report_task_retry(spec, attempt,
                                            "worker crashed")
                    await asyncio.sleep(min(0.05 * (2 ** attempt), 2.0))
                    continue
                err_cls, detail, info = await self._describe_worker_death(
                    outcome)
                if info.get("preempted") and preempt_retries < 3:
                    preempt_retries += 1
                    self._report_task_retry(
                        spec, attempt, "worker preempted by the memory "
                        "monitor (PREEMPT_RESCHEDULE)")
                    await asyncio.sleep(
                        min(0.05 * (2 ** preempt_retries), 2.0))
                    continue
                self._fail_task(spec, serialize_error(err_cls(
                    f"worker died while executing task {spec.name} "
                    f"(after {attempt} retries){detail}")))
                self._release_deps(spec)
                return
            reply = outcome
            if reply.get("app_error") is not None:
                if (not reexported
                        and b"not found in the GCS function table"
                        in reply["app_error"]
                        and await self._maybe_reexport(
                            spec.function.function_hash)):
                    reexported = True
                    # A bounced GCS lost the export; it's restored — retry
                    # without burning a user-visible attempt.
                    continue
                if (spec.task_id.binary() not in self._cancelled_tasks
                        and self._should_retry_app_error(
                            spec, reply["app_error"], attempt)):
                    attempt += 1
                    self._report_task_retry(spec, attempt,
                                            "application error")
                    continue
                self._fail_task(spec, reply["app_error"])
                self._release_deps(spec)
                return
            # Result installation is transactional with dep release and
            # the FINISHED event; results above rpc_put_max_bytes take
            # the sync plasma path (everything smaller is pipelined via
            # _async_plasma_put), a local-socket RPC to the co-located
            # raylet.
            self._accept_results(spec, reply)  # graftlint: disable=async-blocking-transitive
            self._release_deps(spec)
            self._record_task_event(spec, "FINISHED")
            return

    def _report_task_retry(self, spec: TaskSpec, attempt: int,
                           reason: str) -> None:
        """Fire-and-forget TASK_RETRY cluster event; forensics must never
        slow down or fail the retry itself."""
        async def _send():
            try:
                await self.gcs.acall(
                    "report_cluster_event", event_type="TASK_RETRY",
                    message=f"task {spec.name} attempt {attempt}/"
                            f"{spec.max_retries} retrying: {reason}",
                    extra={"task_id": spec.task_id.hex(),
                           "attempt": attempt, "reason": reason},
                    timeout=10)
            except Exception:
                pass

        try:
            asyncio.get_running_loop().create_task(_send())
        except RuntimeError:
            pass

    async def _describe_worker_death(self, outcome: "_WorkerCrashed"):
        """Forensics for a final (retries-exhausted) worker death: exit
        classification + last log lines from the lessor raylet, recent
        same-node cluster events from the GCS. The lessor being
        unreachable while the GCS says its node is DEAD classifies as
        NODE_DEATH. Returns (exception_class, message_suffix, info) —
        the retry loop reads info["preempted"] to rerun memory-monitor
        preemptions instead of failing them."""
        from ray_tpu.observability import events as _events

        err_cls = exc.WorkerCrashedError
        detail = ""
        info: dict = {}
        node_hex = None
        try:
            info = await outcome.lessor.acall(
                "get_worker_exit_info",
                worker_id=outcome.worker_id, timeout=5) or {}
            if not info.get("exit_type"):
                # The raylet's reaper polls every 200ms; the crash was
                # noticed here first. One short retry for the verdict.
                await asyncio.sleep(0.5)
                info = await outcome.lessor.acall(
                    "get_worker_exit_info",
                    worker_id=outcome.worker_id, timeout=5) or {}
            node_hex = info.get("node_id")
        except Exception:
            try:
                nodes = await self.gcs.acall("get_all_nodes", timeout=5)
                lessor_addr = (outcome.lessor.host, outcome.lessor.port)
                for n in nodes:
                    if tuple(n.get("addr") or ()) == lessor_addr:
                        node_hex = n["node_id"].hex()
                        if n.get("state") == "DEAD":
                            info = {"exit_type": "NODE_DEATH"}
                        break
            except Exception:
                pass
        if info.get("oom_killed"):
            err_cls = exc.OutOfMemoryError
            detail = " (OOM-killed by the node memory monitor)"
            info.setdefault("exit_type", "OOM_KILLED")
        elif info.get("preempted"):
            detail = (" (preemptively rescheduled by the node memory "
                      "monitor)")
            info.setdefault("exit_type", "PREEMPT_RESCHEDULE")
        elif info.get("exit_type") == "NODE_DEATH":
            detail = " (the node hosting the worker died)"
        recent = None
        if node_hex:
            try:
                recent = await self.gcs.acall(
                    "list_cluster_events", node_id=node_hex, limit=5,
                    timeout=5)
            except Exception:
                recent = None
        return (err_cls, detail + _events.format_exit_detail(info, recent),
                info)

    def _should_retry_app_error(self, spec: TaskSpec, payload: bytes,
                                attempt: int) -> bool:
        if attempt >= spec.max_retries or spec.retry_exceptions is False:
            return False
        if spec.retry_exceptions is True:
            return True
        try:
            cause, _ = deserialize_error(payload)
            return isinstance(cause, tuple(spec.retry_exceptions))
        except Exception:
            return False

    def _lease_key(self, spec: TaskSpec, demand: ResourceSet) -> str:
        s = spec.scheduling
        return repr((sorted(demand.to_dict().items()), s.kind, s.node_id,
                     s.soft, s.placement_group_id, s.bundle_index,
                     sorted(s.hard_labels.items()),
                     sorted(s.soft_labels.items()), spec.runtime_env,
                     spec.job_id.binary()))

    def _lease_state(self, key: str) -> "_LeaseState":
        st = self._lease_pool.get(key)
        if st is None:
            st = self._lease_pool[key] = _LeaseState()
        return st

    def _hand_lease(self, key: str, st: "_LeaseState", lease,
                    reused: bool = False) -> None:
        lease["_idle_since"] = time.monotonic()
        lease["_reused"] = reused
        if reused:
            st.idle.append(lease)
        else:
            # Fresh grants pair before recycled leases: a grant was issued
            # *for* a specific waiter by the cluster scheduler; honoring it
            # first keeps placement decisions with the raylet.
            st.idle.appendleft(lease)
        st.event.set()
        if not self._lease_pool_sweeper_started:
            self._lease_pool_sweeper_started = True
            spawn_task(self._lease_pool_sweeper())

    async def _lease_pool_sweeper(self):
        """Give leases back to their raylet after a short idle window so
        held workers never starve other owners for long."""
        idle_ttl = 0.5
        while not self._dead:
            await asyncio.sleep(0.1)
            now = time.monotonic()
            for key, st in list(self._lease_pool.items()):
                while st.idle and now - st.idle[0]["_idle_since"] > idle_ttl:
                    lease = st.idle.popleft()
                    try:
                        await lease["_lessor"].acall(
                            "return_worker", worker_id=lease["worker_id"],
                            kill=False,
                            lease_token=lease.get("lease_token"),
                            timeout=10)
                    except Exception:
                        pass
                if (not st.idle and not st.waiters and not st.inflight
                        and not st.pushing):
                    self._lease_pool.pop(key, None)
                    st.event.set()  # wake the dispatcher so it can exit

    async def _dispatch_task(self, spec: TaskSpec):
        """Owner-side lease manager + dispatcher (reference: the direct
        task submitter's leased-worker cache and pipelined lease requests
        in `normal_task_submitter`, `lease_policy.h:56`). Tasks with the
        same scheduling shape share a queue: granted or finished-with
        leases are handed straight to the next waiters — batched into one
        push frame when the function is measured-short — and raylet round
        trips happen only to grow the working set.

        Returns the push reply dict, or None (no lease), or the
        _CANCELLED_SENTINEL, or a _WorkerCrashed instance.
        """
        demand = spec.resources
        strategy = spec.scheduling
        if strategy.kind == "PLACEMENT_GROUP":
            demand = await self._pg_demand(strategy, demand)
            if demand is None:
                return None
        key = self._lease_key(spec, demand)
        st = self._lease_state(key)
        fut = asyncio.get_running_loop().create_future()
        st.waiters.append((spec, fut))
        st.event.set()
        if not st.dispatcher_started:
            st.dispatcher_started = True
            spawn_task(self._lease_dispatcher(key, st))
        self._spawn_lease_requesters(key, st, demand, strategy,
                                     spec.runtime_env)
        # No deadline here: a saturated-but-feasible cluster queues tasks
        # indefinitely (reference pending-task-queue semantics); only the
        # requester resolves a waiter with None when demand stays
        # infeasible past the lease deadline. The periodic wakeup just
        # re-ensures requesters exist (they exit when waiters drain).
        while True:
            done, _ = await asyncio.wait([fut], timeout=30)
            if done:
                return fut.result()
            self._spawn_lease_requesters(key, st, demand, strategy,
                                         spec.runtime_env)

    async def _lease_dispatcher(self, key: str, st: "_LeaseState"):
        """Single consumer per scheduling shape: pairs idle leases with
        waiting tasks and fires batch pushes."""
        while not self._dead:
            try:
                await asyncio.wait_for(st.event.wait(), 30)
            except asyncio.TimeoutError:
                if self._lease_pool.get(key) is not st:
                    return  # state was retired by the sweeper
                continue
            st.event.clear()
            if self._lease_pool.get(key) is not st:
                return
            while st.idle and st.waiters:
                lease = st.idle.popleft()
                if (lease.get("_reused") and st.remote_pending
                        and not self._live_waiters_at_least(
                            st, st.remote_pending + 1)):
                    # Every remaining waiter has a grant pending on another
                    # node (spilled request parked at a remote raylet).
                    # Reusing this finished lease would serialize work on
                    # this node while that node idles; park it instead —
                    # the sweeper returns it if the grants land first.
                    st.idle.appendleft(lease)
                    break
                batch = self._take_batch(st)
                if not batch:
                    st.idle.appendleft(lease)
                    break
                st.pushing += 1
                spawn_task(
                    self._push_batch(key, st, lease, batch))

    @staticmethod
    def _live_waiters_at_least(st: "_LeaseState", k: int) -> bool:
        """True if >= k waiters are still live (future not done). Bounded
        scan: stops at k, so callers comparing against small thresholds
        (inflight caps, remote_pending) stay O(k) on deep queues."""
        if k <= 0:
            return True
        n = 0
        for _spec, fut in st.waiters:
            if not fut.done():
                n += 1
                if n >= k:
                    return True
        return False

    def _take_batch(self, st: "_LeaseState"):
        """Pop the next push batch: one task normally; up to 8 of the same
        function when its measured duration says batching can't hurt
        (amortizes per-frame cost without timesharing long tasks)."""
        batch = []
        while st.waiters and len(batch) < 8:
            spec, fut = st.waiters[0]
            if fut.done():
                st.waiters.popleft()
                continue
            if spec.task_id.binary() in self._cancelled_tasks:
                st.waiters.popleft()
                fut.set_result(_CANCELLED_SENTINEL)
                continue
            if batch:
                if (spec.function.function_hash
                        != batch[0][0].function.function_hash):
                    break
            batch.append(st.waiters.popleft())
            ema = self._fn_dur_ema.get(spec.function.function_hash)
            if ema is None or ema >= 0.005 or spec.num_returns < 0:
                break  # unknown / long / generator: one task per lease
        return batch

    async def _push_batch(self, key: str, st: "_LeaseState", lease, batch):
        worker_addr = tuple(lease["worker_addr"])
        client = self._client_for(worker_addr)
        phases_on = GlobalConfig.sched_phase_instrumentation
        for spec, fut in batch:
            self._inflight_push[spec.task_id.binary()] = worker_addr
            if len(batch) > 1:
                self._inflight_futs[spec.task_id.binary()] = fut
            if phases_on:
                # The lease is paired with this waiter right here —
                # everything before is scheduling (queueing + raylet
                # lease grant), everything after is dispatch.
                now = time.time()
                spec.phase_ts = dict(spec.phase_ts or {})
                spec.phase_ts["LEASE_GRANTED"] = now
                self._record_task_event(spec, "LEASE_GRANTED", ts=now)
            # Push-time RUNNING: live and crashed tasks must render a
            # task bar even if no reply ever arrives; on reply the
            # worker's exec-start-accurate RUNNING supersedes it
            # (timeline keeps the newest event per state).
            self._record_task_event(spec, "RUNNING",
                                    worker_addr=list(worker_addr))
        try:
            try:
                if len(batch) == 1:
                    replies = [await client.acall(
                        "push_task", spec=batch[0][0],
                        tpu_ids=lease.get("tpu_ids", []))]
                else:
                    replies = await client.acall(
                        "push_tasks", specs=[s for s, _ in batch],
                        tpu_ids=lease.get("tpu_ids", []))
            except (ConnectionLost, OSError):
                for spec, fut in batch:
                    self._inflight_push.pop(spec.task_id.binary(), None)
                    self._inflight_futs.pop(spec.task_id.binary(), None)
                    if not fut.done():
                        fut.set_result(_WorkerCrashed(lease["worker_id"],
                                                      lease["_lessor"]))
                await self._discard_lease(lease)
                st.event.set()
                return
            except Exception as e:  # noqa: BLE001 — e.g. RpcError
                # Unknown failure mode: fail the tasks with the real error
                # (not a bogus lease timeout) and return the worker killed
                # — its state is unknowable.
                for spec, fut in batch:
                    self._inflight_push.pop(spec.task_id.binary(), None)
                    self._inflight_futs.pop(spec.task_id.binary(), None)
                    if not fut.done():
                        fut.set_exception(e)
                await self._discard_lease(lease)
                st.event.set()
                return
            for (spec, fut), reply in zip(batch, replies):
                self._inflight_push.pop(spec.task_id.binary(), None)
                self._inflight_futs.pop(spec.task_id.binary(), None)
                wphases = (reply.pop("phases", None)
                           if isinstance(reply, dict) else None)
                if phases_on and wphases:
                    self._record_reply_phases(spec, wphases, worker_addr)
                dur = (reply.pop("dur", None)
                       if isinstance(reply, dict) else None)
                if dur is not None:
                    h = spec.function.function_hash
                    prev = self._fn_dur_ema.get(h)
                    self._fn_dur_ema[h] = (dur if prev is None
                                           else 0.7 * prev + 0.3 * dur)
                if not fut.done():
                    fut.set_result(reply)
            self._hand_lease(key, st, lease, reused=True)
        finally:
            st.pushing -= 1

    async def _discard_lease(self, lease) -> None:
        try:
            await lease["_lessor"].acall(
                "return_worker", worker_id=lease["worker_id"],
                kill=True, lease_token=lease.get("lease_token"),
                timeout=10)
        except Exception:
            pass

    def _spawn_lease_requesters(self, key, st: "_LeaseState", demand,
                                strategy, runtime_env) -> None:
        # One in-flight raylet request per unserved waiter, capped — the
        # requests pipeline through the raylet's queue and grants go to
        # whichever waiter is first.
        want = min(len(st.waiters), 16)
        while st.inflight < want:
            st.inflight += 1
            spawn_task(self._lease_requester(
                key, st, demand, strategy, runtime_env))

    async def _lease_requester(self, key, st: "_LeaseState", demand,
                               strategy, runtime_env):
        client = self.raylet
        deadline = time.monotonic() + GlobalConfig.worker_lease_timeout_ms / 1000
        fast_timeouts = 0
        try:
            while st.waiters and not self._dead:
                if not self._live_waiters_at_least(
                        st, len(st.idle) + st.inflight):
                    # Remaining waiters are already covered by idle leases
                    # (e.g. the grant this requester just handed over, not
                    # yet consumed by the dispatcher) or by the other
                    # in-flight requests (e.g. one parked at a spilled-to
                    # raylet). A surplus request here would lease a worker
                    # nobody will use — or steal the waiter back from an
                    # idle remote node.
                    break
                remote = client is not self.raylet
                st.remote_pending += remote
                req_start = time.monotonic()
                try:
                    reply = await client.acall(
                        "request_worker_lease",
                        demand=demand.to_dict(), job_id=self.job_id.binary(),
                        strategy_kind="DEFAULT" if strategy.kind ==
                        "PLACEMENT_GROUP" else strategy.kind,
                        strategy_node=strategy.node_id, soft=strategy.soft,
                        hard_labels=strategy.hard_labels,
                        soft_labels=strategy.soft_labels,
                        lease_timeout=25.0, runtime_env=runtime_env,
                        owner_id=self.worker_id.binary(),
                        timeout=30.0)
                except (ConnectionLost, OSError):
                    await asyncio.sleep(0.2)
                    client = self.raylet
                    continue
                finally:
                    if remote:
                        st.remote_pending -= 1
                        st.event.set()  # a parked reused lease may now pair
                if reply.get("timeout") and (
                        time.monotonic() - req_start < 5.0):
                    # The raylet gave up on a pop almost immediately: the
                    # node can't spawn workers at all (fork failure). A
                    # saturated-but-healthy cluster instead parks us the
                    # full lease window, so rapid timeouts are a real
                    # failure signal — bound them rather than hot-loop.
                    fast_timeouts += 1
                    if fast_timeouts >= 20:
                        while st.waiters:
                            _spec, fut = st.waiters.popleft()
                            if not fut.done():
                                fut.set_result(None)
                                break
                        fast_timeouts = 0
                    await asyncio.sleep(0.2)
                    continue
                if not reply.get("timeout"):
                    fast_timeouts = 0
                elif remote:
                    # Full-window park timeout on a spilled-to node: go back
                    # to the local raylet to re-evaluate placement instead of
                    # re-parking on a node that may no longer be the pick.
                    client = self.raylet
                if reply.get("granted"):
                    reply["_lessor"] = client
                    self._hand_lease(key, st, reply)
                    client = self.raylet  # next grant starts local again
                    continue
                if reply.get("spillback_to"):
                    client = self._raylet_client(tuple(reply["spillback_to"]))
                    continue
                if reply.get("env_setup_error"):
                    from ray_tpu.runtime_env.manager import (
                        RuntimeEnvSetupError,
                    )

                    while st.waiters:
                        _spec, fut = st.waiters.popleft()
                        if not fut.done():
                            fut.set_exception(RuntimeEnvSetupError(
                                reply["env_setup_error"]))
                            break
                    await asyncio.sleep(0.05)
                    continue
                if reply.get("infeasible"):
                    # Infeasible *now* may become feasible (node still
                    # joining, PG bundle resources propagating); back off
                    # and retry until the lease deadline, as the
                    # reference's infeasible queue does. A feasible-but-
                    # busy cluster instead queues indefinitely inside the
                    # raylet (a saturated cluster must never fail tasks
                    # with a timeout).
                    if time.monotonic() >= deadline:
                        while st.waiters:
                            _spec, fut = st.waiters.popleft()
                            if not fut.done():
                                fut.set_result(None)
                                break
                        deadline = (time.monotonic()
                                    + GlobalConfig.worker_lease_timeout_ms
                                    / 1000)
                    await asyncio.sleep(0.2)
                    continue
                await asyncio.sleep(0.05)
        finally:
            st.inflight -= 1

    async def _pg_demand(self, strategy: SchedulingStrategySpec,
                         demand: ResourceSet) -> Optional[ResourceSet]:
        reply = await self.gcs.acall("wait_placement_group_ready",
                                     pg_id=strategy.placement_group_id,
                                     wait_timeout=55.0, timeout=60.0)
        if reply.get("state") != "CREATED":
            return None
        from ray_tpu._private.resources import pg_task_demand

        return pg_task_demand(demand, strategy.placement_group_id.hex(),
                              strategy.bundle_index)

    def _accept_results(self, spec: TaskSpec, reply: Dict[str, Any]) -> None:
        if spec.num_returns < 0:
            self._accept_generator_results(spec, reply)
            return
        for outer, inners in (reply.get("contained") or {}).items():
            # Return values embedding refs: we own the return object, so
            # we hold the object-keyed borrow on each inner ref until the
            # return object is freed.
            self._adopt_contained(outer, inners)
        for oid, kind, payload in reply["results"]:
            if kind == "inline":
                self._complete_object(oid, inline=payload)
            elif kind == "plasma":
                self.reference_counter.add_location(oid, payload)
                self._complete_object(oid, in_plasma=True)
            elif kind == "error":
                self._complete_object(oid, error=payload)

    def _accept_generator_results(self, spec: TaskSpec,
                                  reply: Dict[str, Any]) -> None:
        tid = spec.task_id.binary()
        count = reply.get("generator_count", len(reply["results"]))
        for i, item in enumerate(reply["results"]):
            self._on_generator_item(tid, i, item)  # no-op if already pushed
        state = self._generators.setdefault(tid, _GeneratorState())
        with state.cond:
            state.total = count
            state.cond.notify_all()
        # The generator ref (index 1) resolves to the list of item refs
        # (num_returns="dynamic" semantics).
        refs = [ObjectRef(spec.generator_item_id(i).binary(), self.addr,
                          self.worker_id.binary()) for i in range(count)]
        self._store_value(spec.return_ids()[0].binary(), refs)

    def _fail_task(self, spec: TaskSpec, error_payload: bytes) -> None:
        self._record_task_event(spec, "FAILED")
        for rid in spec.return_ids():
            self._complete_object(rid.binary(), error=error_payload)
        state = self._generators.get(spec.task_id.binary())
        if state is not None:
            with state.cond:
                state.error = error_payload
                state.cond.notify_all()

    def _release_deps(self, spec: TaskSpec) -> None:
        # Lineage pinning (reference: lineage pinning in reference_count.cc):
        # while the task's spec is kept for reconstruction, its args must
        # stay resolvable — their deps are released only when the lineage is
        # dropped (_drop_lineage), not when the task completes.
        if spec.task_id.binary() in self._lineage:
            return
        for arg in spec.args:
            if arg.is_ref and tuple(arg.owner_addr) == self.addr:
                self.reference_counter.remove_task_dependency(arg.object_id)

    # ======================================================================
    # Actor submission (owner side)
    # ======================================================================
    def _actor_creation_spec(self, cls_name: str, fn_hash, args, kwargs,
                             options: Dict[str, Any]) -> TaskSpec:
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        arg_specs, kw_keys = self._serialize_args(args, kwargs)
        resources = _resources_from_options(options)
        return TaskSpec(
            task_id=task_id, job_id=self.job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            function=FunctionDescriptor("", cls_name, fn_hash),
            args=arg_specs, kwargs_keys=kw_keys, num_returns=0,
            resources=resources, owner_addr=self.addr,
            owner_worker_id=self.worker_id,
            name=options.get("name") or cls_name,
            scheduling=_strategy_from_options(options),
            actor_id=actor_id,
            max_restarts=options.get("max_restarts",
                                     GlobalConfig.actor_max_restarts_default),
            max_task_retries=options.get("max_task_retries", 0),
            max_concurrency=options.get("max_concurrency", 1),
            is_async_actor=options.get("is_async", False),
            is_detached=options.get("lifetime") == "detached",
            actor_name=options.get("name") or "",
            namespace=options.get("namespace") or "default",
            runtime_env=self._prepare_runtime_env(
                options.get("runtime_env")),
        )

    def create_actor(self, cls_payload: bytes, cls_name: str, args, kwargs,
                     options: Dict[str, Any]) -> "Any":
        from ray_tpu.actor import ActorHandle

        fn_hash = self.export_function(cls_payload)
        spec = self._actor_creation_spec(cls_name, fn_hash, args, kwargs,
                                         options)
        reply = self.gcs.call("register_actor", spec=spec)
        if reply.get("error"):
            if options.get("get_if_exists") and reply.get("existing_actor_id"):
                return self.get_actor(options["name"],
                                      options.get("namespace") or "default")
            raise ValueError(reply["error"])
        if not spec.is_detached:
            # Non-detached actors die when all local handles go out of scope.
            self.actor_handles.mark_created(spec.actor_id.binary())
        return ActorHandle(spec.actor_id.binary(), cls_name,
                           options.get("max_task_retries", 0))

    def create_actors(self, cls_payload: bytes, cls_name: str, count: int,
                      args, kwargs, options: Dict[str, Any]) -> List["Any"]:
        """Create `count` identical actors with ONE batched GCS
        registration RPC (the per-member round-trip was the dominant
        serialized cost of a large gang/fleet bring-up)."""
        from ray_tpu.actor import ActorHandle

        fn_hash = self.export_function(cls_payload)  # exported once
        specs = [
            self._actor_creation_spec(cls_name, fn_hash, args, kwargs,
                                      options)
            for _ in range(count)
        ]
        replies = self.gcs.call("register_actors", specs=specs)
        handles = []
        for spec, reply in zip(specs, replies):
            if reply.get("error"):
                raise ValueError(reply["error"])
            if not spec.is_detached:
                self.actor_handles.mark_created(spec.actor_id.binary())
            handles.append(ActorHandle(spec.actor_id.binary(), cls_name,
                                       options.get("max_task_retries", 0)))
        return handles

    def get_actor(self, name: str, namespace: str = "default"):
        from ray_tpu.actor import ActorHandle

        info = self.gcs.call("get_named_actor", name=name, namespace=namespace)
        if info is None:
            raise ValueError(f"no actor named {name!r} in namespace "
                             f"{namespace!r}")
        return ActorHandle(info["actor_id"], info.get("class_name", "Actor"),
                           0)

    def submit_actor_task(self, actor_id: bytes, method_name: str, args,
                          kwargs, options: Dict[str, Any],
                          max_task_retries: int = 0) -> List[ObjectRef]:
        self.drain_releases()
        task_id = TaskID.for_actor_task(ActorID(actor_id))
        arg_specs, kw_keys = self._serialize_args(args, kwargs)
        num_returns = options.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if isinstance(num_returns, str):
            num_returns = {"dynamic": -1, "streaming": -2}[num_returns]
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, task_type=TaskType.ACTOR_TASK,
            function=FunctionDescriptor("", method_name, ""),
            args=arg_specs, kwargs_keys=kw_keys, num_returns=num_returns,
            resources=ResourceSet({}), owner_addr=self.addr,
            owner_worker_id=self.worker_id,
            name=method_name, actor_id=ActorID(actor_id),
            max_task_retries=max_task_retries,
            concurrency_group=options.get("concurrency_group", ""),
            trace_ctx=_current_wire_trace(),
        )
        refs = []
        for rid in spec.return_ids():
            self.reference_counter.add_owned(rid.binary())
            self._entry(rid.binary())
            refs.append(ObjectRef(rid.binary(), self.addr,
                                  self.worker_id.binary()))
        if num_returns < 0:
            # Streaming item pushes may arrive before this coroutine runs.
            self._generators[task_id.binary()] = _GeneratorState()
        self.io.submit(self._run_actor_task(spec))
        if streaming:
            from ray_tpu._private.object_ref import ObjectRefGenerator

            gen = ObjectRefGenerator(task_id.binary(), self.addr,
                                     self.worker_id.binary())
            gen._ref0 = refs[0]
            return [gen]
        return refs

    def _actor_lock(self, actor_id: bytes) -> asyncio.Lock:
        lock = self._actor_submit_locks.get(actor_id)
        if lock is None:
            lock = self._actor_submit_locks[actor_id] = asyncio.Lock()
        return lock

    # -- batched actor submission -------------------------------------------
    # One sender coroutine per actor drains queued calls into multi-spec
    # push frames (reference analogue: the direct actor transport's ordered
    # send queue in core_worker; batching amortizes per-frame pickling and
    # loop wakeups, the difference between ~1.7k and ~10k calls/s here).
    # The single sender also provides the (assign seq, send) ordering the
    # old per-actor lock enforced.
    async def _send_actor_task(self, actor_id: bytes, spec: TaskSpec):
        b = self._actor_batchers.get(actor_id)
        if b is None:
            b = self._actor_batchers[actor_id] = _ActorSendQueue()
            b.task = spawn_task(self._actor_send_loop(actor_id, b))
        fut = asyncio.get_running_loop().create_future()
        b.queue.append((spec, fut))
        b.event.set()
        return await fut

    async def _actor_send_loop(self, actor_id: bytes, b: "_ActorSendQueue"):
        max_batch = 64
        while not self._dead:
            await b.event.wait()
            b.event.clear()
            while b.queue:
                batch = [b.queue.popleft()
                         for _ in range(min(len(b.queue), max_batch))]
                addr = None
                addr_err: Optional[BaseException] = None
                # NOTHING has been sent yet for this batch (no seqs
                # burned), so retrying the address lookup is always
                # safe — a single GCS blip must not fail calls from
                # max_task_retries=0 callers who cannot retry.
                lookup_deadline = (time.monotonic()
                                   + GlobalConfig.actor_unreachable_timeout_s)
                attempt = 0
                while True:
                    try:
                        addr = await self._actor_addr(actor_id)
                        addr_err = None
                        break
                    except Exception as e:  # noqa: BLE001 — GCS outage
                        addr_err = e
                        if (self._dead
                                or time.monotonic() >= lookup_deadline):
                            break
                        attempt += 1
                        await asyncio.sleep(min(1.0, 0.2 * attempt))
                if addr_err is not None:
                    # Lookup deadline exhausted: resolve the batch with
                    # the error and keep the loop alive so later calls
                    # don't enqueue onto a dead sender forever.
                    err = addr_err if isinstance(
                        addr_err, (ConnectionLost, OSError)) \
                        else ConnectionLost(repr(addr_err))
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(type(err)(str(err)))
                    continue
                if addr is None:
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(_ActorAddrUnavailable())
                    continue
                seqs = []
                for _ in batch:
                    seqs.append(self._actor_seq[actor_id])
                    self._actor_seq[actor_id] += 1
                # Pipelined: the next batch is framed while this one's reply
                # is in flight; the worker starts tasks in frame order and
                # the seq machinery keeps per-caller FIFO.
                spawn_task(self._deliver_actor_batch(
                    actor_id, batch, seqs, addr))

    async def _deliver_actor_batch(self, actor_id, batch, seqs, addr):
        """Send one framed batch, resending the SAME sequence numbers on
        transient connection failures while the actor process is alive
        with an unchanged incarnation. Two reasons this retry must live
        HERE: (a) a connect blip to a live actor is a network event, not
        an actor death — callers with max_task_retries=0 must not see
        ActorDiedError for it; (b) seqs are burned at assignment, and a
        dropped frame would leave a permanent gap that wedges the
        worker's in-order start queue for every later call from this
        caller."""
        batched = len(batch) > 1
        prev_inc = self._actor_incarnation.get(actor_id, 0)
        # Deadline, not a small attempt count: on an oversubscribed host
        # a healthy actor worker can be CPU-starved past the 10 s
        # connect timeout many times in a row (observed: a 500-actor
        # readiness sweep after a 1M-task drain). Resending the SAME
        # seqs is safe for any duration — the worker dedups — so
        # persistence costs nothing semantically, while giving up early
        # surfaces a bogus failure for a live actor.
        deadline = (time.monotonic()
                    + GlobalConfig.actor_unreachable_timeout_s)
        attempt = 0
        while True:
            if addr is None:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(_ActorAddrUnavailable())
                return
            client = self._client_for(addr)
            try:
                if batched:
                    reply = await client.acall(
                        "push_actor_tasks", specs=[s for s, _ in batch],
                        seqs=seqs, caller_id=self.worker_id.binary())
                else:
                    reply = await client.acall(
                        "push_actor_task", spec=batch[0][0], seq=seqs[0],
                        caller_id=self.worker_id.binary())
            except (ConnectionLost, OSError) as e:
                self._actor_addr_cache.pop(actor_id, None)
                gcs_down = False
                try:
                    info = await self.gcs.acall(
                        "get_actor_info", actor_id=actor_id, timeout=30)
                except Exception:
                    # GCS unreachable: the actor's fate is UNKNOWN, not
                    # bad — resending the same seqs is safe regardless,
                    # so keep retrying under the deadline instead of
                    # converting a GCS blip into a hard task failure
                    # for max_task_retries=0 callers.
                    info = None
                    gcs_down = True
                if ((gcs_down or (info and info.get("state") == "ALIVE"
                                  and info.get("restarts_used",
                                               0) == prev_inc))
                        and time.monotonic() < deadline):
                    # Same process, still alive (or fate unknowable):
                    # resend the same frame (the worker dedups seqs it
                    # already started).
                    attempt += 1
                    await asyncio.sleep(min(1.0, 0.2 * attempt))
                    if info and info.get("addr"):
                        addr = tuple(info["addr"])
                    elif not gcs_down:
                        try:
                            addr = await self._actor_addr(actor_id)
                        except Exception:
                            pass  # keep the old addr; retry covers it
                    # gcs_down: keep the old addr — a lookup would just
                    # raise again, and an escaped exception here would
                    # orphan every future in the batch.
                    continue
                print(f"[worker] actor delivery giving up after "
                      f"{attempt} resends: state="
                      f"{(info or {}).get('state')} inc="
                      f"{(info or {}).get('restarts_used')} "
                      f"err={type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(ConnectionLost(str(e)))
                return
            except Exception as e:  # noqa: BLE001 — RpcError etc.: a
                # fire-and-forget task swallowing this would leave every
                # caller future pending forever; fail the calls instead.
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return
            replies = reply if batched else [reply]
            for (spec, fut), r in zip(batch, replies):
                if not fut.done():
                    fut.set_result(r)
            return

    async def _run_actor_task(self, spec: TaskSpec) -> None:
        self.actor_handles.task_submitted(spec.actor_id.binary())
        try:
            await self._run_actor_task_inner(spec)
        except Exception as e:  # noqa: BLE001
            self._fail_task(spec, serialize_error(e))
            self._release_deps(spec)
        finally:
            self.actor_handles.task_completed(spec.actor_id.binary())

    async def _run_actor_task_inner(self, spec: TaskSpec) -> None:
        actor_id = spec.actor_id.binary()
        dep_error = await self._resolve_deps(spec)
        if dep_error is not None:
            self._fail_task(spec, dep_error)
            self._release_deps(spec)
            return
        attempt = 0
        while True:
            try:
                reply = await self._send_actor_task(actor_id, spec)
            except _ActorAddrUnavailable:
                self._fail_task(spec, serialize_error(exc.ActorDiedError(
                    f"actor {spec.actor_id} is dead")))
                self._release_deps(spec)
                return
            except (ConnectionLost, OSError):
                self._actor_addr_cache.pop(actor_id, None)
                # The GCS learns of the death via the raylet's worker-exit
                # report, which races this query: an immediate read can
                # return stale ALIVE with an unchanged incarnation and
                # misclassify a plain death as "restarted". Poll until the
                # state moves off the pre-failure snapshot (or ~5s).
                prev_inc = self._actor_incarnation.get(actor_id, 0)
                info = None
                for _ in range(25):
                    info = await self.gcs.acall("get_actor_info",
                                                actor_id=actor_id,
                                                timeout=30)
                    state = (info or {}).get("state")
                    if state != "ALIVE" or (info or {}).get(
                            "restarts_used", 0) != prev_inc:
                        break
                    await asyncio.sleep(0.2)
                state = (info or {}).get("state")
                # Sequence numbers reset only when the actor PROCESS was
                # replaced (incarnation bump), not on a transient network
                # drop to a live actor — the live process keeps its
                # expected_seq counter.
                new_inc = (info or {}).get("restarts_used", 0)
                if new_inc != self._actor_incarnation.get(actor_id, 0):
                    self._actor_incarnation[actor_id] = new_inc
                    self._actor_seq.pop(actor_id, None)
                if state in ("RESTARTING", "PENDING_CREATION", "ALIVE") and (
                        spec.max_task_retries != 0 and
                        (spec.max_task_retries == -1
                         or attempt < spec.max_task_retries)):
                    attempt += 1
                    continue
                if state == "ALIVE":
                    if new_inc == prev_inc:
                        # Never restarted: the delivery layer exhausted
                        # its (long) same-seq resend deadline against a
                        # live but unreachable actor. Say so — calling
                        # this a restart sent earlier debugging down the
                        # wrong path entirely.
                        self._fail_task(spec, serialize_error(
                            exc.ActorUnavailableError(
                                f"actor alive but unreachable while "
                                f"executing {spec.name}: same-seq "
                                f"delivery resends exhausted their "
                                f"deadline (actor_unreachable_timeout_s="
                                f"{GlobalConfig.actor_unreachable_timeout_s}"
                                f" per stage — address lookup and frame "
                                f"delivery each); set max_task_retries "
                                f"to retry automatically")))
                    else:
                        # Actor restarted but this call isn't retryable.
                        self._fail_task(spec, serialize_error(
                            exc.ActorUnavailableError(
                                f"actor restarted while executing "
                                f"{spec.name}; set max_task_retries to "
                                f"retry automatically")))
                else:
                    self._fail_task(spec, serialize_error(exc.ActorDiedError(
                        f"actor died while executing {spec.name}: "
                        f"{(info or {}).get('death_cause')}")))
                self._release_deps(spec)
                return
            if reply.get("app_error") is not None:
                self._fail_task(spec, reply["app_error"])
            else:
                # Same contract as the normal-task path: install results
                # before releasing deps; only >rpc_put_max_bytes results
                # hit the sync plasma leaf.
                self._accept_results(spec, reply)  # graftlint: disable=async-blocking-transitive
            self._release_deps(spec)
            return

    async def _actor_addr(self, actor_id: bytes) -> Optional[Tuple[str, int]]:
        addr = self._actor_addr_cache.get(actor_id)
        if addr is not None:
            return addr
        while True:
            reply = await self.gcs.acall("wait_actor_ready",
                                         actor_id=actor_id,
                                         wait_timeout=55.0, timeout=60.0)
            state = reply.get("state")
            if state == "ALIVE":
                addr = tuple(reply["addr"])
                self._actor_addr_cache[actor_id] = addr
                return addr
            if state == "DEAD" or reply.get("error") == "unknown actor":
                return None
            # PENDING_CREATION / RESTARTING / long-poll window expired:
            # creation backlog (e.g. a 500-actor burst waiting on worker
            # spawns) is not death — calls to a pending actor block until
            # it comes up, as the reference's direct actor transport does.

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        self.gcs.call("kill_actor", actor_id=actor_id, no_restart=no_restart)

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> None:
        """Cancel a task: pre-dispatch it simply never runs; a RUNNING task
        is interrupted on its executing worker (async exception; `force=`
        kills the worker process — reference `CancelTask` force-kill path,
        `core_worker.proto:425`)."""
        tid = ObjectID(ref.binary()).task_id()
        task_id = tid.binary()
        self._cancelled_tasks.add(task_id)
        actor_id = tid.actor_id()
        addr = self._inflight_push.get(task_id)
        if addr is None and not actor_id.is_nil():
            # Actor task: its executing worker is the actor's worker.
            addr = self._actor_addr_cache.get(actor_id.binary())
        if addr is None:
            return

        async def _cancel_running():
            try:
                await self._client_for(addr).acall(
                    "cancel_task", task_id=task_id, force=force, timeout=5)
            except Exception:
                pass

        self.io.submit(_cancel_running())

    # ======================================================================
    # Execution side (RPC handlers)
    # ======================================================================
    async def _h_stack_dump(self):
        """All-thread stack traces (reference: the dashboard's py-spy
        dump route, `profile_manager.py:188` — here via sys._current
        _frames, no external tool). Returns both the structured
        per-thread rows (``threads``) and the joined text blob
        (``stacks``, the shape the dashboard prints)."""
        from ray_tpu.observability import profiling as _profiling

        threads = _profiling.capture_thread_stacks()
        return {"pid": os.getpid(),
                "worker_id": self.worker_id.hex(),
                "threads": threads,
                "stacks": _profiling.format_thread_stacks(threads)}

    async def _h_dump_stacks(self):
        """`ray stack` RPC name (the raylet fans this out per node)."""
        return await self._h_stack_dump()

    async def _h_profile(self, duration_s=5.0, interval_ms=None, hz=None):
        """Wall-clock sampling profile over a StackSampler daemon
        thread: per-thread folded-stack counts + flamegraph.pl text.
        The event loop stays live (the sampler runs on its own thread,
        this handler just sleeps the window), so profiling never blocks
        the worker's task push path. ``interval_ms`` is the legacy
        spelling of the rate; ``hz`` wins when both are given."""
        from ray_tpu.observability import profiling as _profiling

        duration_s = min(float(duration_s),
                         GlobalConfig.profiler_max_duration_s)
        if hz is None and interval_ms is not None:
            hz = 1000.0 / max(float(interval_ms), 1.0)
        sampler = _profiling.StackSampler(hz=hz)
        sampler.start()
        try:
            await asyncio.sleep(duration_s)
        finally:
            result = sampler.stop()
        return {"pid": os.getpid(),
                "worker_id": self.worker_id.hex(),
                "duration_s": result["duration_s"],
                "hz": sampler.hz,
                "samples": result["samples"],
                "dropped": result["dropped"],
                "counts": result["counts"],
                "folded": _profiling.collapse(result["counts"])}

    async def _h_tpu_profile(self, duration_s=1.0, trace_dir=None):
        """Device-trace capture bracket (jax.profiler start/stop_trace)
        on this worker; no-op-with-reason when the process has no TPU
        backend. Runs in an executor thread — start_trace/stop_trace
        block, and the event loop must keep serving task pushes."""
        from ray_tpu.observability import profiling as _profiling

        duration_s = min(float(duration_s),
                         GlobalConfig.profiler_max_duration_s)
        reply = await asyncio.get_running_loop().run_in_executor(
            None, _profiling.capture_tpu_trace, duration_s, trace_dir)
        reply["pid"] = os.getpid()
        reply["worker_id"] = self.worker_id.hex()
        return reply

    async def _h_busy_info(self):
        """Liveness+load probe for the raylet's worker-killing policy: a
        leased worker that is actually executing is a better OOM victim
        than one idling in the lease pool (reference:
        `worker_killing_policy.h:34` picks among workers with assigned
        tasks)."""
        return {"executing": len(self._executing_tids)}

    async def _h_ping(self):
        return "pong"

    async def _h_early_task_result(self, task_id, reply, worker_addr=None):
        """Owner-side receiver for a batch sibling's eager completion (see
        _h_push_tasks): resolves the dispatch future early so dependents
        inside the same push batch can make progress. The sender must
        still be the worker this attempt is inflight on — a delayed push
        from a crashed prior attempt must not resolve a retry's future
        with results stored on the dead worker."""
        if (worker_addr is None
                or self._inflight_push.get(task_id) != tuple(worker_addr)):
            return False
        fut = self._inflight_futs.get(task_id)
        if fut is not None and not fut.done():
            fut.set_result(reply)
        return True

    async def _h_wait_object_status(self, object_id, wait_timeout=10.0):
        """Long-poll variant of get_object_status: blocks server-side until
        the object resolves (or the poll window closes), replacing
        borrower-side fixed-rate polling (reference: owner push/long-poll,
        `core_worker.proto:425`). Never fabricates entries: freed/unknown
        ids answer immediately (a freed object must not block the window,
        and phantom entries would leak)."""
        deadline = asyncio.get_running_loop().time() + min(wait_timeout, 30.0)
        while True:
            status = await self._h_get_object_status(object_id)
            if status.get("status") != "pending":
                return status
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return status
            entry = self._entry(object_id, create=False)
            if entry is None:
                # Unknown here (not yet submitted / already dropped):
                # cheap re-check without creating state.
                await asyncio.sleep(min(0.05, remaining))
                continue
            fut = asyncio.get_running_loop().create_future()
            entry.waiters.append(fut)
            if entry.event.is_set() and not fut.done():
                fut.set_result(None)
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                try:
                    entry.waiters.remove(fut)
                except ValueError:
                    pass

    async def _h_get_object_status(self, object_id):
        entry = self._entry(object_id, create=False)
        if entry is None or not entry.event.is_set():
            if self.reference_counter.is_freed(object_id):
                return {"status": "freed"}
            return {"status": "pending"}
        if entry.error is not None:
            return {"status": "error", "error": entry.error}
        if entry.inline is not None:
            return {"status": "inline", "data": entry.inline}
        return {"status": "plasma",
                "locations": list(self.reference_counter.locations(object_id))}

    async def _h_delete_object_notification(self, object_id):
        mobj = self._mapped.pop(object_id, None)
        if mobj is not None:
            mobj.mark_released()  # the explicit release below covers it
            mobj.close()
            try:
                await self.raylet.acall(
                    "release_object", object_id=object_id,
                    client_id=self.worker_id.binary(), timeout=5)
            except Exception:
                pass
        return True

    async def _h_kill_self(self):
        # Stop accepting work NOW: a task pushed in the window between this
        # reply and os._exit must fail as killed, not silently execute
        # (ray.kill() has already returned to the user by then).
        self._killed = True
        try:  # last-gasp user-metric flush (bounded; best effort)
            from ray_tpu.util.metrics import metric_source, snapshot_records
            recs = snapshot_records()
            if recs:
                await asyncio.wait_for(
                    self.gcs.acall("push_metrics",
                                   source=metric_source(self),
                                   records=recs, timeout=1), 1.0)
        except Exception:
            pass
        asyncio.get_running_loop().call_later(0.02, os._exit, 1)
        return True

    async def _h_cancel_task(self, task_id, force=False):
        self._cancelled_tasks.add(task_id)
        tid_thread = self._executing_tids.get(task_id)
        if tid_thread is not None:
            if force:
                # Reply first, then die: the owner maps the connection loss
                # of a cancelled task to TaskCancelledError, never a retry.
                asyncio.get_running_loop().call_later(0.02, os._exit, 1)
            elif self._thread_task.get(tid_thread) == task_id:
                # The inverse-map check guards against the thread having
                # finished this task and picked up another (async-exc must
                # never land in an innocent task).
                import ctypes

                # Raised at the next bytecode boundary of the executing
                # thread (cannot interrupt a blocking C call — same limit
                # as the reference's non-force cancel).
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid_thread),
                    ctypes.py_object(exc.TaskCancelledError))
        return True

    async def _h_push_task(self, spec: TaskSpec, tpu_ids):
        return await asyncio.get_running_loop().run_in_executor(
            self._task_executor, self._execute_task, spec, tpu_ids)

    async def _h_push_tasks(self, specs, tpu_ids):
        """Batched push: executed sequentially under the caller's single
        lease (the owner only batches functions it has measured as short).

        Every completion except the batch's last is ALSO pushed eagerly to
        the owner (`early_task_result`): results that only rode the
        aggregate reply deadlocked any batch where a later task blocks on
        an earlier sibling's output (the owner can't resolve the sibling
        until the whole batch replies, and the batch can't finish until
        the blocked task gets the sibling's value). The aggregate reply
        remains the reliable path; the eager push is fire-and-forget."""
        loop = asyncio.get_running_loop()
        out = []
        for i, spec in enumerate(specs):
            reply = await loop.run_in_executor(
                self._task_executor, self._execute_task, spec, tpu_ids)
            out.append(reply)
            if i < len(specs) - 1 and tuple(spec.owner_addr) != self.addr:
                spawn_task(self._notify_early_result(spec, reply))
        return out

    async def _notify_early_result(self, spec, reply):
        try:
            owner = self._client_for(tuple(spec.owner_addr))
            await owner.acall(
                "early_task_result", task_id=spec.task_id.binary(),
                reply=reply, worker_addr=list(self.addr), timeout=30)
        except Exception:
            pass    # aggregate reply still delivers it

    def _load_function(self, fn_hash: str):
        fn = self._fn_cache.get(fn_hash)
        if fn is None:
            payload = self.gcs.call("kv_get", namespace="fn", key=fn_hash)
            if payload is None:
                raise exc.RaySystemError(
                    f"function {fn_hash} not found in the GCS function table")
            fn = cloudpickle.loads(payload)
            self._fn_cache[fn_hash] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec):
        values = []
        for arg in spec.args:
            if arg.is_ref:
                ref = ObjectRef(arg.object_id, arg.owner_addr, b"",
                                _register=False)
                values.append(self._get_one(ref, timeout=None))
            else:
                values.append(self.serialization.deserialize(
                    memoryview(arg.inline_data)))
        n_kw = len(spec.kwargs_keys)
        if n_kw:
            args = values[:-n_kw]
            kwargs = dict(zip(spec.kwargs_keys, values[-n_kw:]))
        else:
            args, kwargs = values, {}
        return args, kwargs

    def _mark_log_task(self, spec: Optional[TaskSpec],
                       actor_id_hex: str = "",
                       end_tid: Optional[str] = None) -> None:
        """Bracket this process's log streams with task-attribution
        markers (consumed by the raylet's LogMonitor, never echoed) so
        `get_log(task_id=...)` can slice one task's output out of a
        pooled worker's log file. spec=None closes the open span
        (``end_tid`` hex, or the calling thread's current task)."""
        if self.mode != MODE_WORKER:
            return
        from ray_tpu._private.log_monitor import (
            task_end_marker, task_marker,
        )

        if spec is None:
            tid_hex = end_tid or (self._ctx.task_id.hex()
                                  if self._ctx.task_id else None)
            if tid_hex is None:
                return
            line = task_end_marker(tid_hex)
        else:
            line = task_marker(spec.task_id.hex(), actor_id_hex,
                               spec.name)
        for stream in (sys.stdout, sys.stderr):
            try:
                stream.write(line + "\n")
                stream.flush()
            except Exception:
                pass

    def _execute_task(self, spec: TaskSpec, tpu_ids) -> Dict[str, Any]:
        if spec.task_id.binary() in self._cancelled_tasks:
            return {"results": [], "app_error": serialize_error(
                exc.TaskCancelledError(f"task {spec.name} cancelled"))}
        self._mark_log_task(spec)
        self._ctx.task_id = spec.task_id
        self._ctx.task_name = spec.name
        self._ctx.tpu_ids = list(tpu_ids or [])
        if tpu_ids:
            from ray_tpu.accelerators.tpu import TPUAcceleratorManager

            TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
                [str(i) for i in tpu_ids])
        tid = spec.task_id.binary()
        self._executing_tids[tid] = threading.get_ident()
        self._thread_task[threading.get_ident()] = tid
        # Restore the caller's trace context around the task body (the
        # executor thread is reused, so reset in the finally below).
        from ray_tpu.util import tracing as _tracing

        trace_token = _tracing.activate_wire_context(spec.trace_ctx)
        t_start = time.monotonic()
        # Scheduling-phase clocks, stamped on THIS host as execution
        # proceeds and returned in the reply: the owner lands them in
        # the task-event ring and the sched_phase_seconds histogram.
        phases = ({"WORKER_STARTED": time.time()}
                  if GlobalConfig.sched_phase_instrumentation else None)
        try:
            fn = self._load_function(spec.function.function_hash)
            args, kwargs = self._resolve_args(spec)
            if phases is not None:
                phases["ARGS_READY"] = time.time()
                phases["RUNNING"] = time.time()
            result = fn(*args, **kwargs)
            if spec.num_returns < 0:
                results, count = self._store_generator_returns(spec, result)
                return {"results": results, "generator_count": count,
                        "dur": time.monotonic() - t_start,
                        "phases": phases}
            results, contained = self._store_returns(spec, result)
            return {"results": results, "contained": contained,
                    "dur": time.monotonic() - t_start, "phases": phases}
        except Exception as e:  # noqa: BLE001 — application error
            return {"results": [], "app_error": serialize_error(e),
                    "dur": time.monotonic() - t_start, "phases": phases}
        finally:
            _tracing.deactivate_context(trace_token)
            self._executing_tids.pop(tid, None)
            self._thread_task.pop(threading.get_ident(), None)
            self._mark_log_task(None)
            self._ctx.task_id = None
            self._ctx.task_name = ""

    def _store_returns(self, spec: TaskSpec, result: Any):
        num_returns = spec.num_returns
        if num_returns == 0:
            return []
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task {spec.name} declared num_returns={num_returns} but "
                    f"returned {len(values)} values")
        out = []
        contained = {}
        for rid, value in zip(spec.return_ids(), values):
            oid = rid.binary()
            sobj = self.serialization.serialize(value)
            if self.serialization.last_contained_refs:
                # Refs nested in a return value: the return object's owner
                # is the CALLER, so report them in the reply — the caller
                # registers the object-keyed borrows with the inner owners
                # while our serialize-side pending pin still covers them.
                contained[oid] = [
                    (i, list(a) if a else None)
                    for i, a in self.serialization.last_contained_refs]
            if sobj.total_size <= GlobalConfig.max_direct_call_object_size:
                out.append((oid, "inline", sobj.to_bytes()))
            else:
                self._plasma_put(oid, sobj)
                out.append((oid, "plasma", self.node_id))
        return out, contained

    def _store_generator_returns(self, spec: TaskSpec, result: Any):
        """Execution side of num_returns="dynamic"/"streaming": store each
        yielded item as its own object; streaming additionally reports every
        item to the owner as it is produced (reference:
        `ReportGeneratorItemReturns`, `core_worker.proto:425`)."""
        streaming = spec.num_returns == -2
        owner = None
        if streaming and tuple(spec.owner_addr) != self.addr:
            owner = self._client_for(tuple(spec.owner_addr))
        items = []
        count = 0
        for value in result:
            oid = spec.generator_item_id(count).binary()
            sobj = self.serialization.serialize(value)
            # Refs nested in a yielded item ride along so the owner can
            # adopt object-keyed borrows (same contract as _store_returns).
            contained = [(i, list(a) if a else None)
                         for i, a in self.serialization.last_contained_refs]
            if sobj.total_size <= GlobalConfig.max_direct_call_object_size:
                entry = (oid, "inline", sobj.to_bytes(), contained)
            else:
                self._plasma_put(oid, sobj)
                entry = (oid, "plasma", self.node_id, contained)
            items.append(entry)
            if streaming:
                if owner is not None:
                    # Fire-and-forget, pipelined on the io loop: the
                    # producing thread never blocks a network round trip
                    # per item. A lost push self-heals — the final task
                    # reply re-delivers every item (owner-side dedup).
                    self.io.submit(owner.acall(
                        "report_generator_item",
                        task_id=spec.task_id.binary(), index=count,
                        item=entry, timeout=60))
                else:  # owner == executing worker (self-lease)
                    self._on_generator_item(spec.task_id.binary(), count,
                                            entry)
            count += 1
        return items, count

    # ---- generator plane (owner side) -------------------------------------
    def _on_generator_item(self, task_id: bytes, index: int, item) -> None:
        oid, kind, payload = item[0], item[1], item[2]
        contained = item[3] if len(item) > 3 else None
        entry = self._entry(oid)
        if not entry.event.is_set():
            if (not self.reference_counter.has_ref(oid)
                    and not self.reference_counter.is_freed(oid)):
                # First arrival only — re-produced items after a lineage
                # recovery are already tracked and must not inflate the
                # lineage live count.
                self.reference_counter.add_owned(oid)
                if task_id in self._lineage_live:
                    self._lineage_live[task_id] += 1
            if contained:
                # We own the item object: hold its nested refs until it
                # is freed (first arrival only — re-deliveries would
                # only duplicate the already-held borrows).
                self._adopt_contained(oid, contained)
            if kind == "inline":
                self._complete_object(oid, inline=payload)
            else:
                self.reference_counter.add_location(oid, payload)
                self._complete_object(oid, in_plasma=True)
        state = self._generators.get(task_id)
        if state is not None:
            with state.cond:
                state.produced = max(state.produced, index + 1)
                state.cond.notify_all()

    async def _h_report_generator_item(self, task_id, index, item):
        self._on_generator_item(task_id, index, item)
        return True

    def next_generator_ref(self, task_id: bytes, index: int) -> ObjectRef:
        """Blocks until item `index` of the generator task exists; raises
        StopIteration at the end (ObjectRefGenerator protocol)."""
        state = self._generators.get(task_id)
        if state is None:
            raise RuntimeError(
                f"no generator state for task {task_id.hex()} "
                "(ObjectRefGenerator is only usable in the owner process)")
        with state.cond:
            while True:
                if index < state.produced:
                    break
                if state.error is not None:
                    self._raise_task_error(state.error)
                if state.total is not None and index >= state.total:
                    raise StopIteration
                if not state.cond.wait(timeout=300):
                    raise exc.GetTimeoutError(
                        f"generator item {index} of {task_id.hex()} did not "
                        "arrive within 300s")
        ref_oid = ObjectID.for_task_return(TaskID(task_id),
                                           index + 2).binary()
        return ObjectRef(ref_oid, self.addr, self.worker_id.binary())

    def generator_progress(self, task_id: bytes):
        state = self._generators.get(task_id)
        if state is None:
            return 0, None
        with state.cond:
            return state.produced, state.total

    # ---- lineage / object recovery (owner side) ---------------------------
    def _drop_lineage(self, tid: bytes) -> None:
        self._lineage_live.pop(tid, None)
        spec = self._lineage.pop(tid, None)
        self._generators.pop(tid, None)
        if spec is not None:
            # Release the lineage-pinned arg deps (deferred _release_deps).
            for arg in spec.args:
                if arg.is_ref and tuple(arg.owner_addr) == self.addr:
                    self.reference_counter.remove_task_dependency(
                        arg.object_id)

    def _task_return_oids(self, spec: TaskSpec) -> List[bytes]:
        oids = [rid.binary() for rid in spec.return_ids()]
        if spec.num_returns < 0:
            state = self._generators.get(spec.task_id.binary())
            produced = state.produced if state is not None else 0
            oids += [spec.generator_item_id(i).binary()
                     for i in range(produced)]
        return oids

    def _try_recover_object(self, oid: bytes,
                            timeout: Optional[float] = None) -> bool:
        """Reconstruct a lost plasma object by re-executing its creating
        task (reference: `object_recovery_manager.h:90` RecoverObject +
        lineage in `task_manager.cc:896`). Waits at most `timeout` (caller's
        get() budget) for the re-execution to finish."""
        tid = bytes(oid[:TaskID.SIZE])
        spec = self._lineage.get(tid)
        if spec is None:
            return False
        with self._objects_lock:
            ev = self._recovering.get(tid)
            fresh = ev is None
            if fresh:
                ev = self._recovering[tid] = threading.Event()
        if fresh:
            for roid in self._task_return_oids(spec):
                with self._objects_lock:
                    self._objects[roid] = _PendingObject()
                for node in self.reference_counter.locations(roid):
                    self.reference_counter.remove_location(roid, node)
            state = self._generators.get(tid)
            if state is not None:
                with state.cond:
                    state.produced = 0
                    state.total = None
                    state.error = None
            fut = self.io.submit(self._run_normal_task(spec))

            def _done(_f):
                ev.set()
                self._recovering.pop(tid, None)

            fut.add_done_callback(_done)
        wait_s = 300.0 if timeout is None else min(timeout, 300.0)
        if not ev.wait(timeout=wait_s):
            return False
        return True

    async def _h_recover_object(self, object_id):
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._try_recover_object, object_id)
        return {"ok": ok}

    # ---- actor execution --------------------------------------------------
    async def _h_create_actor(self, spec: TaskSpec, tpu_ids=None):
        loop = asyncio.get_running_loop()

        def _construct():
            # Blocking work (KV fetch, arg gets, __init__) stays off the loop.
            if tpu_ids:
                from ray_tpu.accelerators.tpu import TPUAcceleratorManager

                TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
                    [str(i) for i in tpu_ids])
            self._actor_tpu_ids = list(tpu_ids or [])
            cls = self._load_function(spec.function.function_hash)
            args, kwargs = self._resolve_args(spec)
            return cls(*args, **kwargs)

        try:
            instance = await loop.run_in_executor(self._task_executor,
                                                  _construct)
            self._actor = _ActorState(instance, spec)
            return {"ok": True}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}",
                    "error_payload": serialize_error(e)}

    async def _h_push_actor_task(self, spec: TaskSpec, seq: int,
                                 caller_id: bytes):
        """Ordered execution per caller (reference: ActorSchedulingQueue with
        sequence numbers). Tasks start strictly in sequence order; with
        max_concurrency > 1 they may overlap after starting."""
        actor = self._actor
        if getattr(self, "_killed", False):
            return {"results": [], "app_error": serialize_error(
                exc.ActorDiedError("actor was killed via ray.kill"))}
        if actor is None:
            return {"results": [], "app_error": serialize_error(
                exc.ActorUnavailableError("actor is not initialized yet"))}
        loop = asyncio.get_running_loop()
        if seq < actor.expected_seq[caller_id]:
            # Retry of a task we may have already started (at-least-once
            # under max_task_retries): execute immediately, out of band.
            return await self._execute_actor_task(actor, spec)
        my_turn = loop.create_future()
        actor.pending[caller_id][seq] = my_turn
        self._advance_caller_queue(actor, caller_id)
        await my_turn
        # In-order START, concurrent execution: bump the expected sequence as
        # soon as this task begins so the next one can start while we run
        # (bounded by max_concurrency via the executor/semaphore).
        actor.expected_seq[caller_id] = seq + 1
        self._advance_caller_queue(actor, caller_id)
        return await self._execute_actor_task(actor, spec)

    async def _h_push_actor_tasks(self, specs, seqs, caller_id):
        """Batched form of push_actor_task: one frame, N ordered calls."""
        return list(await asyncio.gather(*[
            self._h_push_actor_task(spec, seq, caller_id)
            for spec, seq in zip(specs, seqs)]))

    def _advance_caller_queue(self, actor: _ActorState, caller_id: bytes):
        expected = actor.expected_seq[caller_id]
        fut = actor.pending[caller_id].pop(expected, None)
        if fut is not None and not fut.done():
            fut.set_result(None)

    async def _execute_actor_task(self, actor: _ActorState, spec: TaskSpec):
        loop = asyncio.get_running_loop()
        if spec.task_id.binary() in self._cancelled_tasks:
            return {"results": [], "app_error": serialize_error(
                exc.TaskCancelledError(f"task {spec.name} cancelled"))}
        method_name = spec.function.qualname
        from ray_tpu.dag import COMPILED_STAGE_METHOD

        if method_name == COMPILED_STAGE_METHOD:
            # Compiled-DAG resident stage loop (ray_tpu.dag): occupies
            # this actor's executor until the DAG is torn down.
            from ray_tpu.dag import run_compiled_stage

            method = lambda payload: run_compiled_stage(  # noqa: E731
                actor.instance, payload)
        else:
            method = getattr(actor.instance, method_name, None)
        if method is None:
            return {"results": [], "app_error": serialize_error(
                AttributeError(f"actor has no method {method_name!r}"))}
        self._mark_log_task(spec, actor.spec.actor_id.hex())
        # Restore the caller's trace context for the method body. Each
        # push_actor_task dispatch runs as its own asyncio task, so the
        # contextvar keeps concurrent requests in one max_concurrency>1
        # actor on disjoint trace identities. Sync methods hop to a
        # pool thread (contextvars don't cross run_in_executor), so the
        # callable re-activates the wire context thread-side.
        from ray_tpu.util import tracing as _tracing

        trace_token = _tracing.activate_wire_context(spec.trace_ctx)
        try:
            args, kwargs = await loop.run_in_executor(
                self._task_executor, self._resolve_args, spec)
            if actor.is_async and asyncio.iscoroutinefunction(method):
                async with actor.semaphore:
                    result = await method(*args, **kwargs)
            else:
                wire = spec.trace_ctx

                def _call_traced():
                    tok = _tracing.activate_wire_context(wire)
                    try:
                        return method(*args, **kwargs)
                    finally:
                        _tracing.deactivate_context(tok)

                result = await loop.run_in_executor(
                    actor.executor_for(spec.concurrency_group),
                    _call_traced)
            if spec.num_returns < 0:
                # Actor generator methods stream like normal-task ones:
                # each yielded item becomes an object, pushed to the owner
                # as produced (num_returns="streaming").
                results, count = await loop.run_in_executor(
                    self._task_executor, self._store_generator_returns,
                    spec, result)
                return {"results": results, "generator_count": count}
            results, contained = await loop.run_in_executor(
                self._task_executor, self._store_returns, spec, result)
            return {"results": results, "contained": contained}
        except Exception as e:  # noqa: BLE001
            return {"results": [], "app_error": serialize_error(e)}
        finally:
            _tracing.deactivate_context(trace_token)
            self._mark_log_task(None, end_tid=spec.task_id.hex())

    # ======================================================================
    # Runtime context / shutdown
    # ======================================================================
    def current_task_id(self) -> Optional[TaskID]:
        return self._ctx.task_id

    def current_tpu_ids(self) -> List[int]:
        if self._actor is not None:
            return list(getattr(self, "_actor_tpu_ids", []))
        return list(self._ctx.tpu_ids)

    def current_actor_id(self) -> Optional[bytes]:
        if self._actor is not None:
            return self._actor.spec.actor_id.binary()
        return None

    def async_get(self, refs):
        return asyncio.to_thread(self.get_objects, refs, None)

    def shutdown(self):
        # Deferred GC releases first, while the raylet connection is
        # still alive — pending view releases queued in the last drainer
        # interval would otherwise leave client read-pins until the
        # raylet's client-death sweep.
        try:
            self.drain_releases()
        except Exception:
            pass
        # Tell owners we no longer hold any borrowed refs (best effort —
        # their liveness sweep reaps us anyway if this is lost).
        for oid, addr in self.reference_counter.drain_borrows():
            try:
                self._client_for(tuple(addr)).call(
                    "release_borrower", object_id=oid,
                    key=self.worker_id.binary(), timeout=2)
            except Exception:
                pass
        # Final task-event + user-metric flush before the GCS connection
        # closes (synchronous: the io loop dies with us).
        try:
            with self._task_events_lock:
                batch, self._task_events = self._task_events, []
            if batch:
                self.gcs.call("push_task_events", events=batch, timeout=5)
        except Exception:
            pass
        try:
            from ray_tpu.util import metrics as _metrics
            _metrics.flush()
        except Exception:
            pass
        if len(self._mapped):
            try:
                for mobj in list(self._mapped.values()):
                    mobj.mark_released()  # bulk release below covers them
                self.raylet.call("release_objects",
                                 object_ids=list(self._mapped.keys()),
                                 client_id=self.worker_id.binary(),
                                 timeout=5)
            except Exception:
                pass
        # Hand parked reusable leases back before the connections close so
        # their resources free immediately (not via job-cleanup timers).
        for st in list(self._lease_pool.values()):
            while st.idle:
                lease = st.idle.popleft()
                try:
                    lease["_lessor"].call(
                        "return_worker", worker_id=lease["worker_id"],
                        kill=False, lease_token=lease.get("lease_token"),
                        timeout=5)
                except Exception:
                    pass
        self._lease_pool.clear()
        self._dead = True
        # Drop the whole ref graph now: a long-lived driver accumulates
        # millions of counter entries and GC over them after the worker
        # object dies dominates interpreter time.
        try:
            self.reference_counter.clear()
        except Exception:
            pass
        for b in self._actor_batchers.values():
            if b.task is not None:
                try:
                    self.io.loop.call_soon_threadsafe(b.task.cancel)
                except Exception:
                    pass
        self._actor_batchers.clear()
        try:
            self.server.stop()
        except Exception:
            pass
        for client in ([self.gcs, self.raylet]
                       + list(self._worker_clients.values())
                       + list(self._raylet_clients.values())):
            try:
                client.close()
            except Exception:
                pass
        for mobj in self._mapped.values():
            mobj.close()
        self._mapped.clear()
        set_global_worker(None)


# ---------------------------------------------------------------------------
# Option helpers
# ---------------------------------------------------------------------------

def _resources_from_options(options: Dict[str, Any]) -> ResourceSet:
    res = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    num_tpus = options.get("num_tpus")
    if num_tpus is not None:
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        ok, msg = TPUAcceleratorManager.validate_resource_request_quantity(
            num_tpus)
        if not ok:
            raise ValueError(msg)
        res[TPU] = num_tpus
    accelerator_type = options.get("accelerator_type")
    if accelerator_type:
        res[f"TPU-{accelerator_type}"] = 0.001
    res["CPU"] = 1 if num_cpus is None else num_cpus
    if options.get("memory"):
        res["memory"] = options["memory"]
    return ResourceSet(res)


def _strategy_from_options(options: Dict[str, Any]) -> SchedulingStrategySpec:
    strategy = options.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategySpec()
    if strategy == "SPREAD":
        return SchedulingStrategySpec(kind="SPREAD")
    # Strategy objects from ray_tpu.util.scheduling_strategies
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return SchedulingStrategySpec(
            kind="PLACEMENT_GROUP",
            placement_group_id=strategy.placement_group.id,
            bundle_index=strategy.placement_group_bundle_index,
            capture_child_tasks=strategy.placement_group_capture_child_tasks)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategySpec(kind="NODE_AFFINITY",
                                      node_id=strategy.node_id,
                                      soft=strategy.soft)
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return SchedulingStrategySpec(kind="NODE_LABEL",
                                      hard_labels=strategy.hard or {},
                                      soft_labels=strategy.soft or {})
    raise ValueError(f"unknown scheduling strategy: {strategy!r}")
