"""Usage stats (reference: `_private/usage/usage_lib.py` +
`dashboard/modules/usage_stats/` — opt-out telemetry pings).

This build is air-gapped by design, so the collector writes the report
locally (session dir `usage_stats.json`) instead of POSTing it; the
schema mirrors the reference's payload (cluster metadata, library usage
tags, counters). Disable with RAY_TPU_USAGE_STATS_ENABLED=0 — the same
opt-out contract as the reference.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Set

_tags: Dict[str, str] = {}
_library_usages: Set[str] = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False")


def record_library_usage(name: str) -> None:
    """Called by each AI library at import/use time (reference:
    `record_library_usage` in usage_lib)."""
    _library_usages.add(name)


def record_extra_usage_tag(key: str, value: str) -> None:
    _tags[str(key)] = str(value)


def get_library_usages() -> List[str]:
    return sorted(_library_usages)


def generate_report(cluster_metadata: Dict[str, Any]) -> Dict[str, Any]:
    import ray_tpu

    return {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "session_id": cluster_metadata.get("session_id"),
        "collect_timestamp_ms": int(time.time() * 1000),
        "os": sys.platform,
        "python_version": sys.version.split()[0],
        "ray_tpu_version": getattr(ray_tpu, "__version__", "0.0.0"),
        "total_num_nodes": cluster_metadata.get("num_nodes"),
        "total_num_cpus": cluster_metadata.get("num_cpus"),
        "total_num_tpus": cluster_metadata.get("num_tpus"),
        "libraries_used": get_library_usages(),
        "extra_usage_tags": dict(_tags),
    }


def write_report(session_dir: str,
                 cluster_metadata: Dict[str, Any]) -> str | None:
    """Write the local usage report; returns its path (None if opted
    out or unwritable)."""
    if not usage_stats_enabled():
        return None
    try:
        path = os.path.join(session_dir, "usage_stats.json")
        with open(path, "w") as f:
            json.dump(generate_report(cluster_metadata), f, indent=2)
        return path
    except OSError:
        return None
