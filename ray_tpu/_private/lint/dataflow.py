"""Per-function dataflow for graftlint: CFGs + obligation tracking.

The PR-6/PR-12 passes were structural — they matched call shapes inside
a scope and could not see *paths*.  The bug classes that motivated this
engine are all path problems:

- a binding read *after* it flowed into a donated ``jit`` position on
  some path (use-after-donate reads freed HBM);
- a split-phase ``start_*`` handle that misses its ``wait_*`` on an
  early return or exception edge (the mesh hangs);
- an ``ObjectRef`` dropped or overwritten before anything consumed it
  (the object stays pinned in plasma forever).

Three layers live here:

1. :func:`build_cfg` — a per-function control-flow graph.  Branches,
   loops (with ``else``), ``try``/``except``/``finally`` (exceptional
   edges are tagged so passes can opt in or out), ``with``, early
   ``return``/``raise``/``break``/``continue``.  Inside a ``try`` body
   with handlers every statement gets its own block, so the state
   flowing into a handler is the union of the states after *each*
   statement the exception could interrupt.
2. :func:`solve` — a worklist fixpoint over block states.  States are
   joined by union (may-analysis): a finding means "there EXISTS a path
   on which the obligation goes wrong", which is exactly the split-phase
   / ObjectRef contract ("on every path").
3. :class:`ObligationEngine` — the shared abstract interpretation both
   value-obligation passes (split-phase handles, ObjectRefs) configure:
   values created by calls, bound to names (including containers via
   ``append``/subscript stores), discharged by matching consumers or by
   escaping (return / passed to a call), violated by drop, overwrite,
   ``del``, or reaching function exit still live.

Everything is pure stdlib ``ast``; no code under analysis ever runs.
"""

from __future__ import annotations

import ast
from typing import (
    Callable, Dict, FrozenSet, Iterable, Iterator, List, Optional,
    Sequence, Set, Tuple,
)

__all__ = [
    "Block", "CFG", "build_cfg", "cfgs_for_module", "solve",
    "walk_no_scope", "load_names", "ObligationEngine", "Violation",
    "yield_points", "effective_roots", "lexical_locks", "held_locksets",
]

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# ------------------------------------------------------------------ CFG


class Block:
    """A straight-line run of statements.

    ``stmts`` holds the AST nodes *evaluated at this point*: simple
    statements as-is, branch/loop tests as bare expression nodes, and
    ``For``/``With``/``ExceptHandler`` nodes standing in for their
    binding effect (helpers know to read only the parts that execute
    at the construct's head, never the body).
    """

    __slots__ = ("id", "stmts", "succs", "preds")

    def __init__(self, bid: int):
        self.id = bid
        self.stmts: List[ast.AST] = []
        self.succs: List[Tuple["Block", bool]] = []   # (target, is_exc)
        self.preds: List[Tuple["Block", bool]] = []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"B{self.id}({len(self.stmts)} stmts)"


class CFG:
    def __init__(self, func: ast.AST):
        self.func = func
        self.blocks: List[Block] = []
        self.entry: Block = None  # type: ignore[assignment]
        self.exit: Block = None   # type: ignore[assignment]

    def block_at(self, lineno: int) -> Optional[Block]:
        """First block holding a statement that starts on ``lineno``
        (test helper)."""
        for b in self.blocks:
            for s in b.stmts:
                if getattr(s, "lineno", None) == lineno:
                    return b
        return None

    def reachable(self) -> Set[Block]:
        seen = {self.entry}
        stack = [self.entry]
        while stack:
            for succ, _ in stack.pop().succs:
                if succ not in seen:
                    seen.add(succ)
                    stack.append(succ)
        return seen


class _Builder:
    def __init__(self, func: ast.AST):
        self.cfg = CFG(func)
        # (break target, continue target, finally-stack depth at entry)
        self.loops: List[Tuple[Block, Block, int]] = []
        # innermost-last: handler entry blocks of enclosing try's
        self.handlers: List[List[Block]] = []
        self.finallies: List[List[ast.stmt]] = []
        # >0 → one statement per block (inside a try body with handlers)
        self.split = 0

    def new_block(self) -> Block:
        b = Block(len(self.cfg.blocks))
        self.cfg.blocks.append(b)
        return b

    @staticmethod
    def connect(a: Optional[Block], b: Optional[Block],
                exc: bool = False) -> None:
        if a is None or b is None:
            return
        a.succs.append((b, exc))
        b.preds.append((a, exc))

    def build(self) -> CFG:
        self.cfg.entry = self.new_block()
        self.cfg.exit = self.new_block()
        end = self.seq(getattr(self.cfg.func, "body", []), self.cfg.entry)
        self.connect(end, self.cfg.exit)
        return self.cfg

    # ---------------------------------------------------------- helpers

    def append(self, stmt: ast.AST, cur: Block) -> Block:
        cur.stmts.append(stmt)
        if self.split:
            nxt = self.new_block()
            self.connect(cur, nxt)
            return nxt
        return cur

    def seq(self, stmts: Sequence[ast.stmt],
            cur: Optional[Block]) -> Optional[Block]:
        for s in stmts:
            if cur is None:
                # Dead code after return/raise/break: keep building so
                # nested defs are still discovered, but nothing flows in.
                cur = self.new_block()
            cur = self.stmt(s, cur)
        return cur

    def run_finallies(self, cur: Block, down_to: int = 0) -> Block:
        """Inline fresh copies of the active ``finally`` bodies (innermost
        first) onto an abrupt exit path (return/break/continue)."""
        for fin in reversed(self.finallies[down_to:]):
            nxt = self.seq(fin, cur)
            cur = nxt if nxt is not None else self.new_block()
        return cur

    # ------------------------------------------------------- statements

    def stmt(self, node: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(node, ast.If):
            return self._if(node, cur)
        if isinstance(node, (ast.While,)):
            return self._while(node, cur)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            return self._for(node, cur)
        if isinstance(node, ast.Try):
            return self._try(node, cur)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # Linear: the context exprs/bindings happen at the head, the
            # body runs inline. __exit__ cleanup is invisible to the AST.
            cur = self.append(node, cur)
            return self.seq(node.body, cur)
        if isinstance(node, ast.Return):
            cur = self.append(node, cur)
            cur = self.run_finallies(cur)
            self.connect(cur, self.cfg.exit)
            return None
        if isinstance(node, ast.Raise):
            cur = self.append(node, cur)
            if self.handlers:
                for h in self.handlers[-1]:
                    self.connect(cur, h, exc=True)
            else:
                cur = self.run_finallies(cur)
                self.connect(cur, self.cfg.exit, exc=True)
            return None
        if isinstance(node, ast.Break):
            target, _, depth = self.loops[-1]
            cur = self.run_finallies(cur, depth)
            self.connect(cur, target)
            return None
        if isinstance(node, ast.Continue):
            _, target, depth = self.loops[-1]
            cur = self.run_finallies(cur, depth)
            self.connect(cur, target)
            return None
        if isinstance(node, ast.Match):
            return self._match(node, cur)
        # Simple statement (incl. nested def/class: a plain binding).
        return self.append(node, cur)

    def _if(self, node: ast.If, cur: Block) -> Optional[Block]:
        cur = self.append(node.test, cur)
        then_start = self.new_block()
        self.connect(cur, then_start)
        then_end = self.seq(node.body, then_start)
        if node.orelse:
            else_start = self.new_block()
            self.connect(cur, else_start)
            else_end = self.seq(node.orelse, else_start)
        else:
            else_end = cur
        if then_end is None and else_end is None:
            return None
        join = self.new_block()
        self.connect(then_end, join)
        self.connect(else_end, join)
        return join

    @staticmethod
    def _const_true(test: ast.expr) -> bool:
        return isinstance(test, ast.Constant) and bool(test.value) is True

    def _while(self, node: ast.While, cur: Block) -> Optional[Block]:
        head = self.new_block()
        self.connect(cur, head)
        head.stmts.append(node.test)
        after = self.new_block()
        self.loops.append((after, head, len(self.finallies)))
        body_start = self.new_block()
        self.connect(head, body_start)
        body_end = self.seq(node.body, body_start)
        self.connect(body_end, head)
        self.loops.pop()
        if not self._const_true(node.test):
            # Normal loop exit (test false): through else, or straight out.
            if node.orelse:
                else_start = self.new_block()
                self.connect(head, else_start)
                else_end = self.seq(node.orelse, else_start)
                self.connect(else_end, after)
            else:
                self.connect(head, after)
        return after if after.preds else None

    def _for(self, node, cur: Block) -> Optional[Block]:
        # ``for`` bodies are modeled as executing AT LEAST once: the
        # loop exit flows from the end of an iteration, never straight
        # from the head.  The overlap idiom starts chunk 0's collective
        # before a ``for c in range(n_chunks)`` that always runs — a
        # zero-trip edge would flag every such schedule on an
        # infeasible path.  The cost is a missed finding when a
        # genuinely-empty iterable skips the body's discharge
        # (precision over recall, as everywhere in this engine).
        head = self.new_block()
        self.connect(cur, head)
        head.stmts.append(node)   # helpers read .iter (load) + .target (bind)
        after = self.new_block()
        self.loops.append((after, head, len(self.finallies)))
        body_start = self.new_block()
        self.connect(head, body_start)
        body_end = self.seq(node.body, body_start)
        self.connect(body_end, head)
        self.loops.pop()
        if node.orelse:
            else_start = self.new_block()
            self.connect(body_end, else_start)
            else_end = self.seq(node.orelse, else_start)
            self.connect(else_end, after)
        else:
            self.connect(body_end, after)
        return after if after.preds else None

    def _try(self, node: ast.Try, cur: Block) -> Optional[Block]:
        if node.finalbody:
            self.finallies.append(node.finalbody)
        handler_entries: List[Block] = []
        if node.handlers:
            for h in node.handlers:
                he = self.new_block()
                he.stmts.append(h)   # binds ``except E as name``
                handler_entries.append(he)
            self.handlers.append(handler_entries)
            self.split += 1
        body_start = self.new_block()
        self.connect(cur, body_start)
        lo = body_start.id
        body_end = self.seq(node.body, body_start)
        hi = len(self.cfg.blocks)
        if node.handlers:
            self.split -= 1
            self.handlers.pop()
            # The exception can interrupt the body anywhere: the state
            # after each body statement may flow into every handler.
            for b in self.cfg.blocks[lo:hi]:
                if b in handler_entries:
                    continue
                for he in handler_entries:
                    self.connect(b, he, exc=True)
        if node.orelse and body_end is not None:
            body_end = self.seq(node.orelse, body_end)
        handler_ends = [self.seq(h.body, he)
                        for h, he in zip(node.handlers, handler_entries)]
        ends = [e for e in [body_end] + handler_ends if e is not None]
        if node.finalbody:
            self.finallies.pop()
            fstart = self.new_block()
            for e in ends:
                self.connect(e, fstart)
            # Unhandled-exception path: finally runs, then re-raises.
            for b in self.cfg.blocks[lo:hi]:
                if b is not fstart and b not in handler_entries:
                    self.connect(b, fstart, exc=True)
            fend = self.seq(node.finalbody, fstart)
            if fend is not None and not ends:
                # Only abrupt exits reach the finally: it never falls out.
                self.connect(fend, self.cfg.exit, exc=True)
                return None
            return fend
        if not ends:
            return None
        join = self.new_block()
        for e in ends:
            self.connect(e, join)
        return join

    def _match(self, node, cur: Block) -> Optional[Block]:
        cur = self.append(node.subject, cur)
        ends = []
        exhaustive = False
        for case in node.cases:
            start = self.new_block()
            self.connect(cur, start)
            if case.guard is not None:
                start.stmts.append(case.guard)
            ends.append(self.seq(case.body, start))
            if (isinstance(case.pattern, ast.MatchAs)
                    and case.pattern.pattern is None
                    and case.guard is None):
                exhaustive = True   # bare ``case _:``
        ends = [e for e in ends if e is not None]
        if not exhaustive:
            ends.append(cur)
        if not ends:
            return None
        join = self.new_block()
        for e in ends:
            self.connect(e, join)
        return join


def build_cfg(func: ast.AST) -> CFG:
    """CFG for one ``def``/``async def`` (body only; nested defs are
    opaque single statements)."""
    return _Builder(func).build()


def cfgs_for_module(mod) -> Dict[ast.AST, CFG]:
    """Every function's CFG, cached on the ModuleInfo (several passes
    walk the same functions in one run)."""
    cache = getattr(mod, "_graftlint_cfgs", None)
    if cache is None:
        cache = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cache[node] = build_cfg(node)
        mod._graftlint_cfgs = cache
    return cache


# --------------------------------------------------------------- solver


def solve(cfg: CFG,
          transfer: Callable[[Block, object], object],
          initial: object,
          join: Callable[[object, object], object],
          follow_exc: bool = True,
          max_iter: int = 4000) -> Dict[Block, object]:
    """Worklist fixpoint: returns the IN state of every reached block.

    ``transfer(block, in_state) -> out_state`` must be monotone w.r.t.
    ``join`` (set-union states are). ``follow_exc=False`` ignores
    exceptional edges (passes where a raise path is not the bug)."""
    in_states: Dict[Block, object] = {cfg.entry: initial}
    work = [cfg.entry]
    iters = 0
    while work:
        iters += 1
        if iters > max_iter:   # pathological CFG: bail, report nothing new
            break
        b = work.pop()
        out = transfer(b, in_states[b])
        for succ, exc in b.succs:
            if exc and not follow_exc:
                continue
            cur = in_states.get(succ)
            joined = out if cur is None else join(cur, out)
            if cur is None or joined != cur:
                in_states[succ] = joined
                if succ not in work:
                    work.append(succ)
    return in_states


# ------------------------------------------------------- AST utilities


def walk_no_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s subtree without entering nested function/lambda
    bodies (comprehensions are entered: they evaluate here)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if n is not node and isinstance(n, _SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(n))


def effective_exprs(stmt: ast.AST) -> List[ast.expr]:
    """The expressions a CFG block statement actually evaluates *at this
    program point* (a ``For`` head evaluates its iterable, not its
    body)."""
    if isinstance(stmt, ast.expr):               # branch/loop test
        return [stmt]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.ExceptHandler):
        return [stmt.type] if stmt.type is not None else []
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Assign):
        return [stmt.value]
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Assert):
        return [stmt.test] + ([stmt.msg] if stmt.msg else [])
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    return []


def bound_names(stmt: ast.AST) -> List[str]:
    """Plain names (re)bound by this block statement."""
    out: List[str] = []

    def targets(t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, ast.Starred):
            targets(t.value)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets(t)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        targets(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                targets(item.optional_vars)
    elif isinstance(stmt, ast.ExceptHandler):
        if stmt.name:
            out.append(stmt.name)
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        out.append(stmt.name)
    return out


def deleted_names(stmt: ast.AST) -> List[str]:
    if not isinstance(stmt, ast.Delete):
        return []
    return [t.id for t in stmt.targets if isinstance(t, ast.Name)]


def load_names(expr: ast.expr) -> List[ast.Name]:
    """Name nodes in Load context under ``expr`` (nested scopes
    excluded)."""
    return [n for n in walk_no_scope(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]


def base_name(expr: ast.expr) -> Optional[str]:
    """The tracked name an argument expression refers to: a plain Name,
    or the container behind a subscript/star (``handles[c]`` → handles)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Subscript) and isinstance(expr.value, ast.Name):
        return expr.value.id
    if isinstance(expr, ast.Starred):
        return base_name(expr.value)
    return None


# ------------------------------------------------- concurrency helpers
#
# Shared substrate for the graftrace race passes (await-atomicity,
# lockset-consistency): where a coroutine can be suspended, and which
# locks guard a given program point.

_HEAD_ONLY = (ast.For, ast.AsyncFor, ast.With, ast.AsyncWith,
              ast.ExceptHandler)


def effective_roots(stmt: ast.AST) -> List[ast.AST]:
    """The subtrees a CFG block statement actually evaluates: head-only
    nodes (``For``/``With``/``ExceptHandler``) contribute just their
    head expressions, nested def/class statements contribute nothing
    (their bodies run elsewhere), everything else is itself."""
    if isinstance(stmt, _HEAD_ONLY):
        return list(effective_exprs(stmt))
    if isinstance(stmt, _SCOPE_NODES + (ast.ClassDef,)):
        return []
    return [stmt]


def yield_points(stmt: ast.AST) -> List[ast.AST]:
    """The suspension points this block statement evaluates: every
    ``await`` in its effective extent, plus the statement itself for an
    ``async for`` head (``__anext__`` awaits each iteration) and an
    ``async with`` entry (``__aenter__`` awaits). At each of these the
    event loop may run other coroutines of the same object."""
    pts: List[ast.AST] = []
    if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
        pts.append(stmt)
    for root in effective_roots(stmt):
        pts.extend(n for n in walk_no_scope(root)
                   if isinstance(n, ast.Await))
    return pts


def lexical_locks(fn: ast.AST) -> Dict[int, FrozenSet[str]]:
    """``id(node) -> lock names held lexically at that node`` for every
    node under ``fn``, from ``with``/``async with`` on lock-like context
    managers (:func:`_ast_util.lockish`). Lexical, not CFG-based: the
    CFG inlines ``with`` bodies, so the extent of the critical section
    is only visible in the source tree. Nested scopes are not entered —
    their bodies run under their own discipline."""
    from ray_tpu._private.lint._ast_util import lockish

    out: Dict[int, FrozenSet[str]] = {}

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        out[id(node)] = held
        if node is not fn and isinstance(node, _SCOPE_NODES):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            names = frozenset(
                t for t in (lockish(i.context_expr) for i in node.items)
                if t is not None)
            for item in node.items:
                visit(item, held)
            for child in node.body:
                visit(child, held | names)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(fn, frozenset())
    return out


def held_locksets(cfg: CFG) -> Dict[int, FrozenSet[str]]:
    """``id(stmt) -> locks acquired via .acquire() and not yet released``
    at each block statement: a must-lockset worklist analysis (join =
    intersection, so a lock counts only when held on *every* path in).
    Complements :func:`lexical_locks` for the explicit acquire/release
    style."""
    from ray_tpu._private.lint._ast_util import lockish

    def stmt_effect(stmt: ast.AST,
                    held: FrozenSet[str]) -> FrozenSet[str]:
        for root in effective_roots(stmt):
            for n in walk_no_scope(root):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("acquire", "release")):
                    continue
                name = lockish(n.func.value)
                if name is None:
                    continue
                held = (held | {name} if n.func.attr == "acquire"
                        else held - {name})
        return held

    def transfer(block: Block, state: FrozenSet[str]) -> FrozenSet[str]:
        for stmt in block.stmts:
            state = stmt_effect(stmt, state)
        return state

    in_states = solve(cfg, transfer, frozenset(),
                      lambda a, b: a & b)
    out: Dict[int, FrozenSet[str]] = {}
    for block, state in in_states.items():
        for stmt in block.stmts:
            out[id(stmt)] = state
            state = stmt_effect(stmt, state)
    return out


# Receiver methods that stash a value into the receiver container (the
# obligation transfers to the container binding rather than escaping).
_CONTAINER_METHODS = {"append", "add", "insert", "extend", "appendleft"}

_LIVE = "live"
_DONE = "done"


class _State:
    """obligs: obligation id -> possible statuses; binds: name ->
    obligation ids the name may hold."""

    __slots__ = ("obligs", "binds")

    def __init__(self,
                 obligs: Optional[Dict[int, FrozenSet[str]]] = None,
                 binds: Optional[Dict[str, FrozenSet[int]]] = None):
        self.obligs = obligs or {}
        self.binds = binds or {}

    def copy(self) -> "_State":
        return _State(dict(self.obligs), dict(self.binds))

    def __eq__(self, other) -> bool:
        return (isinstance(other, _State) and self.obligs == other.obligs
                and self.binds == other.binds)

    def __hash__(self):  # pragma: no cover - states are not dict keys
        raise TypeError("unhashable")

    @staticmethod
    def join(a: "_State", b: "_State") -> "_State":
        obligs = dict(a.obligs)
        for oid, st in b.obligs.items():
            obligs[oid] = obligs.get(oid, frozenset()) | st
        binds = dict(a.binds)
        for name, ids in b.binds.items():
            binds[name] = binds.get(name, frozenset()) | ids
        return _State(obligs, binds)


class Violation:
    """A raw engine violation, turned into a Finding by the pass."""

    __slots__ = ("kind", "origin", "node", "detail")

    def __init__(self, kind: str, origin: ast.AST, node: ast.AST,
                 detail: str = ""):
        self.kind = kind       # dropped|overwritten|deleted|exit|double|
        self.origin = origin   # the creating call      # mismatch
        self.node = node       # where it went wrong
        self.detail = detail


class ObligationEngine:
    """Shared value-obligation analysis.  Subclasses configure:

    - :meth:`creation_key` — a call that creates an obligation (returns
      an opaque key used for matching, or None);
    - :meth:`discharge_key` — a call that explicitly discharges
      obligations flowing into its arguments (split-phase ``wait_*``);
      return None when any use discharges (ObjectRefs);
    - ``follow_exc`` — whether exception edges count as paths;
    - ``report_double`` / ``report_mismatch`` — emit those kinds.

    Escape = discharge: a value returned, yielded, awaited, stored into
    an attribute, or passed to any call we can't see through is assumed
    consumed — the engine is tuned to flag only what it can prove is
    dropped on some path, never to second-guess an escape.
    """

    follow_exc = True
    report_double = False
    report_mismatch = False
    # True → ANY Load of a bound name discharges (ObjectRefs: any read
    # may store/consume the ref). False → only escapes discharge
    # (split-phase: reading a handle does not wait it).
    loads_consume = False

    # -- hooks ---------------------------------------------------------
    def creation_key(self, call: ast.Call) -> Optional[str]:
        raise NotImplementedError

    def discharge_key(self, call: ast.Call) -> Optional[str]:
        return None

    def keys_match(self, creation: str, discharge: str) -> bool:
        return creation == discharge

    # -- driver --------------------------------------------------------
    def analyze(self, cfg: CFG) -> List[Violation]:
        self._violations: Dict[Tuple[str, int, int], Violation] = {}
        self._origins: Dict[int, ast.AST] = {}
        self._keys: Dict[int, str] = {}
        # Storing into a PARAMETER container escapes to the caller —
        # only locally-created containers are tracked stashes.
        args = getattr(cfg.func, "args", None)
        self._params: Set[str] = set()
        if args is not None:
            self._params = {a.arg for a in (args.posonlyargs + args.args
                                            + args.kwonlyargs)}
            for va in (args.vararg, args.kwarg):
                if va is not None:
                    self._params.add(va.arg)
        # Names a nested def/lambda reads are closure captures: a value
        # bound to one stays reachable through the closure, so it is
        # never "dropped" here no matter what this frame does with the
        # binding afterwards.
        self._captured: Set[str] = set()
        for n in ast.walk(cfg.func):
            if n is cfg.func or not isinstance(n, _SCOPE_NODES):
                continue
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                            ast.Load):
                    self._captured.add(sub.id)
        self._pre_status: Dict[int, FrozenSet[str]] = {}

        def transfer(block: Block, st: _State) -> _State:
            st = st.copy()
            for stmt in block.stmts:
                self._transfer_stmt(stmt, st)
            return st

        in_states = solve(cfg, transfer, _State(), _State.join,
                          follow_exc=self.follow_exc)
        exit_state = in_states.get(cfg.exit)
        if exit_state is not None:
            for oid, statuses in exit_state.obligs.items():
                if _LIVE in statuses:
                    origin = self._origins[oid]
                    self._emit("exit", origin, origin)
        return list(self._violations.values())

    def _emit(self, kind: str, origin: ast.AST, node: ast.AST,
              detail: str = "") -> None:
        key = (kind, getattr(origin, "lineno", 0),
               getattr(node, "lineno", 0))
        if key not in self._violations:
            self._violations[key] = Violation(kind, origin, node, detail)

    # -- per-statement transfer ---------------------------------------
    def _new_oblig(self, call: ast.Call, key: str, st: _State) -> int:
        oid = id(call)
        if oid in st.obligs and oid not in self._pre_status:
            # Same creation site re-executed (loop back edge): remember
            # the PREVIOUS iteration's status so a same-statement rebind
            # judges the old value, not the one just created.
            self._pre_status[oid] = st.obligs[oid]
        self._origins[oid] = call
        self._keys[oid] = key
        st.obligs[oid] = frozenset([_LIVE])
        return oid

    def _discharge_ids(self, ids: Iterable[int], dkey: str, st: _State,
                       at: ast.AST, precise: bool = True) -> None:
        """``precise=False`` → the discharge went through a container
        (``wait(handles[i])``, comprehension over a stash): we can't
        tell WHICH element it hit, so discharge everything but never
        call it a double-wait."""
        for oid in ids:
            statuses = st.obligs.get(oid)
            if statuses is None:
                continue
            ck = self._keys[oid]
            if not self.keys_match(ck, dkey):
                if self.report_mismatch:
                    self._emit("mismatch", self._origins[oid], at,
                               detail=f"{ck} vs {dkey}")
                continue
            if self.report_double and precise \
                    and statuses == frozenset([_DONE]):
                self._emit("double", self._origins[oid], at)
            st.obligs[oid] = frozenset([_DONE])

    def _consume_ids(self, ids: Iterable[int], st: _State) -> None:
        for oid in ids:
            if oid in st.obligs:
                st.obligs[oid] = frozenset([_DONE])

    def _kill_binding(self, name: str, st: _State, node: ast.AST,
                      kind: str) -> None:
        """Rebind/del of ``name``: obligations only it still holds and
        that may still be live are lost on this path."""
        old = st.binds.pop(name, frozenset())
        if name in self._captured:
            return   # a closure still reaches it: losing OUR binding is fine
        for oid in old:
            statuses = self._pre_status.get(
                oid, st.obligs.get(oid, frozenset()))
            if _LIVE not in statuses:
                continue
            aliased = any(oid in ids for n, ids in st.binds.items())
            if not aliased:
                self._emit(kind, self._origins[oid], node)
                st.obligs[oid] = frozenset([_DONE])   # report once

    def _transfer_stmt(self, stmt: ast.AST, st: _State) -> None:
        self._pre_status = {}
        # Pure alias (``h2 = h``): copy the binding, consume nothing.
        if (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Name)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            src = st.binds.get(stmt.value.id)
            tgt = stmt.targets[0].id
            if tgt != stmt.value.id:
                self._kill_binding(tgt, st, stmt, "overwritten")
            if src:
                st.binds[tgt] = src
            return

        created_binds: Dict[str, Set[int]] = {}
        for expr in effective_exprs(stmt):
            self._process_expr(expr, stmt, st, created_binds)

        # Rebinds: overwrite-while-live, then install fresh bindings.
        for name in bound_names(stmt):
            self._kill_binding(name, st, stmt, "overwritten")
            if name in created_binds:
                st.binds[name] = frozenset(created_binds[name])
        # Creations routed into a container (``handles[i] = start(...)``,
        # ``refs.append(...)``) extend that container's binding.
        for name, ids in created_binds.items():
            if name not in bound_names(stmt):
                st.binds[name] = st.binds.get(name, frozenset()) \
                    | frozenset(ids)

        for name in deleted_names(stmt):
            self._kill_binding(name, st, stmt, "deleted")

    # Fates for a creation found inside an expression tree.
    def _process_expr(self, expr: ast.expr, stmt: ast.AST, st: _State,
                      created_binds: Dict[str, Set[int]]) -> None:
        parents: Dict[int, ast.AST] = {}
        for n in walk_no_scope(expr):
            for c in ast.iter_child_nodes(n):
                parents.setdefault(id(c), n)

        calls = [n for n in walk_no_scope(expr) if isinstance(n, ast.Call)]

        # 1. Explicit dischargers (wait_*): discharge what their args hold.
        immediately_discharged: Set[int] = set()
        comp_targets = self._comprehension_iters(expr)
        for call in calls:
            dkey = self.discharge_key(call)
            if dkey is None:
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                inner = arg.value if isinstance(arg, ast.Starred) else arg
                if isinstance(inner, ast.Call):
                    ck = self.creation_key(inner)
                    if ck is not None:
                        # wait_x(start_x(...)): created and discharged
                        # in place; still key-checked.
                        oid = self._new_oblig(inner, ck, st)
                        self._discharge_ids([oid], dkey, st, call)
                        immediately_discharged.add(id(inner))
                        continue
                name = base_name(inner)
                if name is None and isinstance(inner, ast.Name):
                    name = inner.id
                if name is not None:
                    # A comprehension variable stands for elements of the
                    # iterated container: discharge the container.
                    precise = isinstance(inner, ast.Name) \
                        and inner.id not in comp_targets
                    name = comp_targets.get(name, name)
                    self._discharge_ids(st.binds.get(name, ()), dkey, st,
                                        call, precise=precise)

        # 2. Creations and their fate.
        for call in calls:
            if id(call) in immediately_discharged:
                continue
            ck = self.creation_key(call)
            if ck is None:
                continue
            fate, container = self._fate(call, expr, stmt, parents)
            if fate == "bind":
                if container in self._captured:
                    continue   # closure-reachable binding: escapes
                oid = self._new_oblig(call, ck, st)
                created_binds.setdefault(container, set()).add(oid)
            elif fate == "dropped":
                self._emit("dropped", call, call)
            # "escaped": consumed by a call/return/await/attr — no oblig.

        # 3. Generic consumption: every name (or container) flowing into
        # any call escapes to that callee; returns/yields escape to the
        # caller.  AugAssign reads its target.
        consumed: Set[str] = set()
        for call in calls:
            for sub in walk_no_scope(call):
                if sub is call:
                    continue
                if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                            ast.Load):
                    consumed.add(comp_targets.get(sub.id, sub.id))
        if isinstance(stmt, ast.Return) or isinstance(
                getattr(stmt, "value", None), (ast.Yield, ast.YieldFrom)):
            for n in load_names(expr):
                consumed.add(n.id)
        for n in walk_no_scope(expr):
            if isinstance(n, ast.Await):
                for ln in load_names(n.value):
                    consumed.add(ln.id)
            elif isinstance(n, ast.Attribute) and isinstance(
                    n.value, ast.Name) and isinstance(
                        n.value.ctx, ast.Load) and not isinstance(
                            parents.get(id(n)), ast.Call):
                # ``obj.attr = h`` / reading a field: treat the base as
                # used (attribute escapes are untrackable).
                consumed.add(n.value.id)
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.target,
                                                          ast.Name):
            consumed.add(stmt.target.id)
        if self.loads_consume:
            for ln in load_names(expr):
                consumed.add(comp_targets.get(ln.id, ln.id))
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    # ``self.h = ref`` / ``d[k] = ref``: escapes into the
                    # structure unless the structure is a tracked local
                    # container (then the binding transfer below holds it).
                    tgt_container = base_name(t)
                    for ln in load_names(stmt.value):
                        if tgt_container is not None and isinstance(
                                t, ast.Subscript) and \
                                tgt_container not in self._params:
                            ids = st.binds.get(ln.id)
                            if ids:
                                created_binds.setdefault(
                                    tgt_container, set()).update(ids)
                        else:
                            consumed.add(ln.id)
        # ``lst.append(h)`` routes h into lst instead of escaping.
        for call in calls:
            f = call.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _CONTAINER_METHODS
                    and isinstance(f.value, ast.Name)
                    and f.value.id not in self._params):
                recv = f.value.id
                for arg in call.args:
                    nm = base_name(arg)
                    if nm is None:
                        continue
                    ids = st.binds.get(nm)
                    if ids:
                        created_binds.setdefault(recv, set()).update(ids)
                        consumed.discard(nm)

        for name in consumed:
            self._consume_ids(st.binds.get(name, ()), st)

    @staticmethod
    def _comprehension_iters(expr: ast.expr) -> Dict[str, str]:
        """comprehension target name -> iterated container name, for
        ``[wait(h) for h in handles]``-style discharges."""
        out: Dict[str, str] = {}
        for n in walk_no_scope(expr):
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                for gen in n.generators:
                    if isinstance(gen.target, ast.Name) and isinstance(
                            gen.iter, ast.Name):
                        out[gen.target.id] = gen.iter.id
        return out

    def _fate(self, call: ast.Call, root: ast.expr, stmt: ast.AST,
              parents: Dict[int, ast.AST]) -> Tuple[str, str]:
        """("bind", name) | ("dropped", "") | ("escaped", "")."""
        # Walk up: inside another call → escapes to it; inside await /
        # yield → consumed; wrapped only in container displays → binds
        # to the assignment target.
        n: ast.AST = call
        while True:
            p = parents.get(id(n))
            if p is None:
                break
            if isinstance(p, ast.Call):
                # ``handles.append(start(...))``: the fresh obligation is
                # stashed in the receiver container, not consumed.
                f = p.func
                if (n is not f and isinstance(f, ast.Attribute)
                        and f.attr in _CONTAINER_METHODS
                        and isinstance(f.value, ast.Name)
                        and f.value.id not in self._params):
                    return "bind", f.value.id
                return "escaped", ""
            if isinstance(p, (ast.Await, ast.Yield, ast.YieldFrom,
                              ast.Return, ast.comprehension)):
                return "escaped", ""
            if isinstance(p, (ast.Tuple, ast.List, ast.Set, ast.Dict,
                              ast.Starred, ast.ListComp, ast.SetComp,
                              ast.GeneratorExp, ast.DictComp,
                              ast.IfExp)):
                n = p
                continue
            # Arbitrary expression context (h + 1, not isinstance-able):
            # treat as escaped — we cannot track it.
            if not isinstance(p, (ast.Expr, ast.Assign, ast.AnnAssign,
                                  ast.AugAssign, ast.Return)):
                return "escaped", ""
            n = p
            break

        if isinstance(stmt, ast.Return):
            return "escaped", ""
        if isinstance(stmt, ast.Assign):
            # Tuple-to-tuple: bind elementwise when alignment is obvious.
            if (len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Tuple)
                    and isinstance(stmt.value, ast.Tuple)
                    and len(stmt.targets[0].elts)
                    == len(stmt.value.elts)):
                for t, v in zip(stmt.targets[0].elts, stmt.value.elts):
                    if v is call and isinstance(t, ast.Name):
                        return "bind", t.id
            bound = []
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    bound.append(t.id)
                elif isinstance(t, ast.Subscript):
                    cont = base_name(t)
                    if cont is not None and cont not in self._params:
                        bound.append(cont)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    # start() under a tuple target without alignment:
                    # every Name target may hold it.
                    bound.extend(e.id for e in t.elts
                                 if isinstance(e, ast.Name))
            if bound:
                return "bind", bound[0]
            return "escaped", ""
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            return "bind", stmt.target.id
        if isinstance(stmt, ast.Expr):
            return "dropped", ""
        # Condition / iterable / with-item position: not trackable.
        return "escaped", ""
