"""lockset-consistency: attrs guarded sometimes, bare other times.

RacerD's core observation, scaled to this codebase: you don't need a
full happens-before proof to catch most data races — it is enough to
see that ``self._replicas`` is read under ``self._lock`` in one method
and written with no lock in another, *and* that the two methods run on
different strands of execution. The lock discipline the class itself
claims (by locking the attr anywhere at all) is the spec; a bare write
is the violation.

Per class, three ingredients:

- **locksets** — for every ``self.<attr>`` access, the locks held at
  that statement: lexical ``with``/``async with`` blocks on lock-like
  objects plus explicit ``.acquire()``/``.release()`` pairs tracked as
  a must-analysis through the function CFG (intersection join: a lock
  counts only if held on every path in).
- **origin inference** — which strand each method runs on: ``async
  def`` methods run on the event loop; ``run`` on a Thread subclass,
  ``threading.Thread(target=self.m)`` / ``Timer`` targets,
  ``executor.submit(self.m)`` / ``run_in_executor(.., self.m)``
  callbacks, and ``__del__`` (GC finalizes on an arbitrary thread) run
  on their own threads; everything else is an API method called from
  whoever holds the object. Origins propagate through ``self.m()``
  call edges to a fixpoint; methods reachable only from ``__init__``
  are single-threaded by construction and ignored.
- **evidence** — an attr is reported only when its accesses span more
  than one origin (or two distinct thread entry points): a value
  touched from one strand only cannot race, locked or not.

Two rules, ranked: ``lockset-cross-origin-write`` — the bare write
itself runs on a background thread or the event loop (a poll loop
scribbling over state the request path reads under the lock: the worst
shape); ``lockset-inconsistent-write`` — the bare write is in an API
method while locked accesses exist elsewhere.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Set, Tuple

from ray_tpu._private.lint._ast_util import (
    call_name, enclosing_class_map, kwarg, lockish,
)
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import (
    cfgs_for_module, held_locksets, lexical_locks,
)
from ray_tpu._private.lint.race import (
    stmt_self_reads, stmt_self_writes,
)

_INITISH = {"__init__", "__new__", "__post_init__"}

# Spawn shapes whose self-method argument becomes a thread entry point.
_THREAD_CTORS = ("Thread", "Timer")
_THREAD_DISPATCH = ("submit", "run_in_executor", "call_soon_threadsafe")


class _Access:
    __slots__ = ("attr", "kind", "locks", "origins", "method", "line")

    def __init__(self, attr, kind, locks, origins, method, line):
        self.attr = attr
        self.kind = kind          # "read" | "write"
        self.locks = locks        # frozenset of lock names held
        self.origins = origins    # frozenset of origin tags
        self.method = method
        self.line = line


def _self_method_arg(call: ast.Call) -> List[str]:
    """Names m for every ``self.m`` passed as an argument."""
    out = []
    args = list(call.args) + [kw.value for kw in call.keywords]
    for a in args:
        if isinstance(a, ast.Attribute) \
                and isinstance(a.value, ast.Name) and a.value.id == "self":
            out.append(a.attr)
    return out


@register
class LocksetConsistencyPass(LintPass):
    name = "lockset-consistency"
    rules = ("lockset-cross-origin-write", "lockset-inconsistent-write")
    description = ("self.<attr> written bare in one method but accessed "
                   "under a lock in another, across thread/event-loop "
                   "origins")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        _owner, classes = enclosing_class_map(mod.tree)
        cfgs = cfgs_for_module(mod)
        for clsname, clsnode in classes.items():
            out.extend(self._check_class(mod, clsname, clsnode, cfgs))
        return out

    # -------------------------------------------------------- origins

    def _infer_origins(self, clsnode, methods) -> Dict[str, FrozenSet[str]]:
        seeds: Dict[str, Set[str]] = {m: set() for m in methods}
        thread_entries: Set[str] = set()
        is_thread_subclass = any(
            "Thread" in ast.unparse(b) for b in clsnode.bases)
        for name, fn in methods.items():
            if isinstance(fn, ast.AsyncFunctionDef):
                seeds[name].add("loop")
            if name == "run" and is_thread_subclass:
                seeds[name].add("thread")
                thread_entries.add(name)
            if name == "__del__":
                seeds[name].add("thread")
                thread_entries.add(name)
        # Spawn sites anywhere in the class body.
        for fn in methods.values():
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                cname = call_name(n)
                tail = cname.rsplit(".", 1)[-1]
                targets: List[str] = []
                if tail in _THREAD_CTORS:
                    t = kwarg(n, "target")
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        targets.append(t.attr)
                    targets.extend(a for a in _self_method_arg(n)
                                   if a not in targets)
                elif tail in _THREAD_DISPATCH:
                    targets.extend(_self_method_arg(n))
                for t in targets:
                    if t in seeds:
                        seeds[t].add("thread")
                        thread_entries.add(t)
        for init in _INITISH:
            if init in seeds:
                seeds[init].add("init")

        # Propagate through self.m() edges to a fixpoint.
        edges: Dict[str, Set[str]] = {m: set() for m in methods}
        for name, fn in methods.items():
            for n in ast.walk(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == "self" \
                        and n.func.attr in methods:
                    edges[name].add(n.func.attr)
        origins = {m: frozenset(s) for m, s in seeds.items()}
        changed = True
        while changed:
            changed = False
            for name, callees in edges.items():
                for callee in callees:
                    merged = origins[callee] | origins[name]
                    if merged != origins[callee]:
                        origins[callee] = merged
                        changed = True
        self._thread_entries = thread_entries
        return {m: (o if o else frozenset({"api"}))
                for m, o in origins.items()}

    # -------------------------------------------------------- analysis

    def _check_class(self, mod, clsname, clsnode, cfgs):
        methods = {c.name: c for c in clsnode.body
                   if isinstance(c, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not methods:
            return
        origins = self._infer_origins(clsnode, methods)

        accesses: Dict[str, List[_Access]] = {}
        for name, fn in methods.items():
            if name in _INITISH:
                continue
            org = origins[name]
            if org == frozenset({"init"}):
                continue   # reachable from __init__ only: single strand
            cfg = cfgs.get(fn)
            if cfg is None:
                continue
            lex = lexical_locks(fn)
            held = held_locksets(cfg)
            for block in cfg.blocks:
                for stmt in block.stmts:
                    locks = (lex.get(id(stmt), frozenset())
                             | held.get(id(stmt), frozenset()))
                    line = getattr(stmt, "lineno", 0)
                    writes = stmt_self_writes(stmt)
                    for attr in writes:
                        accesses.setdefault(attr, []).append(_Access(
                            attr, "write", locks, org, name, line))
                    for attr in stmt_self_reads(stmt) - writes:
                        accesses.setdefault(attr, []).append(_Access(
                            attr, "read", locks, org, name, line))

        for attr, accs in sorted(accesses.items()):
            if lockish(ast.Name(id=attr, ctx=ast.Load())):
                continue   # the lock itself
            locked = [a for a in accs if a.locks]
            if not locked:
                continue   # no discipline claimed anywhere
            bare_writes = [a for a in accs
                           if a.kind == "write" and not a.locks]
            if not bare_writes:
                continue
            cats = frozenset().union(*(a.origins for a in accs))
            entry_methods = {a.method for a in accs
                             if a.method in self._thread_entries}
            if len(cats - {"init"}) < 2 and len(entry_methods) < 2:
                continue   # single strand: cannot race
            example = locked[0]
            locks_txt = ", ".join(sorted(example.locks))
            seen: Set[Tuple[str, int]] = set()
            for w in bare_writes:
                key = (w.attr, w.line)
                if key in seen:
                    continue
                seen.add(key)
                cross = bool(w.origins & {"thread", "loop"})
                rule = ("lockset-cross-origin-write" if cross
                        else "lockset-inconsistent-write")
                worg = "/".join(sorted(w.origins - {"init"})) or "api"
                eorg = "/".join(sorted(example.origins - {"init"})) \
                    or "api"
                yield mod.finding(
                    rule, w.line,
                    f"{clsname}.{w.method}() writes self.{attr} with no "
                    f"lock, but {clsname}.{example.method}() "
                    f"({example.kind}s it at line {example.line}) holds "
                    f"{locks_txt}; this write runs on {worg} while the "
                    f"locked access runs on {eorg} — take the lock here "
                    f"or document why the race is benign")
