"""actor-reentrancy: awaiting a call chain back into the same actor.

An actor with the default ``max_concurrency=1`` executes one method at
a time. A method that *waits* on a ``.remote()`` call to its own
handle therefore waits on work that can only start after the current
method returns:

    @ray_tpu.remote
    class Pipeline:
        async def step(self):
            return await self.compute.remote(1)   # never completes

``deadlock-self-get`` already catches the synchronous
``ray_tpu.get(self.m.remote())`` shape. This pass adds the two shapes
it cannot see: the *await* form (``await self.m.remote()``, directly
or through a local ref), and the *chain* form — an entry method whose
transitive self-call chain (resolved through the package call graph,
so helpers defined on a base class count) reaches a self-wait buried
in a helper. The chain finding points at the entry call site and
prints the path, because that is the frame a wedged-actor stack dump
will show.

Classes that *declare* ``max_concurrency > 1`` are skipped: their
event loop can interleave the awaited call back in, so reentrant
awaits are legal there (and the await-atomicity pass polices what they
do to shared state instead).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name, kwarg, literal
from ray_tpu._private.lint.callgraph import get_call_graph
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.passes.deadlock import (
    _is_get_call, _is_remote_decorated,
)


def _max_concurrency(clsnode: ast.ClassDef) -> int:
    for dec in clsnode.decorator_list:
        if isinstance(dec, ast.Call):
            v = literal(kwarg(dec, "max_concurrency"))
            if isinstance(v, int):
                return v
    return 1


def _self_remote_target(call: ast.Call) -> Optional[str]:
    """``self.<m>.remote(...)`` -> m (exactly that shape: a call on a
    *stored handle* like ``self._worker.f.remote`` is a different
    actor)."""
    parts = call_name(call).split(".")
    if len(parts) == 3 and parts[0] == "self" and parts[2] == "remote":
        return parts[1]
    return None


def _walk_own(fn) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _self_waits(fn) -> List[Tuple[ast.AST, str, str]]:
    """(site, target method, form) for every point where ``fn``
    synchronously waits on a .remote() call into its own actor. Form is
    "await" or "get"."""
    refs: Dict[str, str] = {}     # local name -> target method
    for n in _walk_own(fn):
        if isinstance(n, ast.Assign):
            found = [t for sub in ast.walk(n.value)
                     if isinstance(sub, ast.Call)
                     for t in [_self_remote_target(sub)] if t]
            if found:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        refs[t.id] = found[0]
    out: List[Tuple[ast.AST, str, str]] = []
    for n in _walk_own(fn):
        if isinstance(n, ast.Await):
            v = n.value
            for sub in ast.walk(v):
                if isinstance(sub, ast.Call):
                    t = _self_remote_target(sub)
                    if t:
                        out.append((n, t, "await"))
                        break
            else:
                base = v
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in refs:
                    out.append((n, refs[base.id], "await"))
        elif isinstance(n, ast.Call) and _is_get_call(n):
            for a in n.args:
                hit = None
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Call):
                        hit = _self_remote_target(sub)
                        if hit:
                            break
                if hit is None and isinstance(a, ast.Name) \
                        and a.id in refs:
                    hit = refs[a.id]
                if hit:
                    out.append((n, hit, "get"))
                    break
    return out


@register
class ActorReentrancyPass(LintPass):
    name = "actor-reentrancy"
    rules = ("actor-reentrant-await", "actor-reentrant-chain")
    description = ("awaits on the actor's own .remote() calls — direct "
                   "or through a helper chain — in max_concurrency=1 "
                   "actors")

    def __init__(self):
        self._mods: List[ModuleInfo] = []

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        graph = get_call_graph(self._mods)
        for mod in self._mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) \
                        and _is_remote_decorated(node) \
                        and _max_concurrency(node) <= 1:
                    yield from self._check_class(mod, node, graph)

    def _check_class(self, mod, clsnode, graph):
        methods = {c.name: c for c in clsnode.body
                   if isinstance(c, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        waits = {name: _self_waits(fn) for name, fn in methods.items()}

        # Direct await-form findings (the get form is deadlock-self-get
        # territory already).
        for name, sites in waits.items():
            for site, target, form in sites:
                if form != "await":
                    continue
                yield mod.finding(
                    "actor-reentrant-await", site,
                    f"{clsnode.name}.{name}() awaits "
                    f"self.{target}.remote(): this actor runs one "
                    f"method at a time, so the awaited call can only "
                    f"start after {name}() returns — guaranteed "
                    f"deadlock (call the method directly, or raise "
                    f"max_concurrency and guard the shared state)")

        # Chain form: entry -> self.g() -> ... -> a self-wait, resolved
        # through the call graph so base-class helpers count.
        has_wait: Dict[str, List[str]] = {
            name: [name] for name, sites in waits.items() if sites}
        edges: Dict[str, List[Tuple[ast.Call, str]]] = {}
        for name, fn in methods.items():
            fi = graph.by_node.get(id(fn))
            if fi is None:
                continue
            for call, callee in graph.direct_calls(fi):
                if callee is None or callee.node is fn:
                    continue
                if isinstance(call.func, ast.Attribute) \
                        and isinstance(call.func.value, ast.Name) \
                        and call.func.value.id in ("self", "cls"):
                    edges.setdefault(name, []).append(
                        (call, callee.name))
        changed = True
        while changed:
            changed = False
            for name, outs in edges.items():
                if name in has_wait:
                    continue
                for _call, callee in outs:
                    if callee in has_wait and callee != name:
                        has_wait[name] = [name] + has_wait[callee]
                        changed = True
                        break
        for name, outs in sorted(edges.items()):
            for call, callee in outs:
                if callee not in has_wait or callee == name:
                    continue
                chain = [name] + has_wait[callee]
                yield mod.finding(
                    "actor-reentrant-chain", call,
                    f"{clsnode.name}.{name}() calls "
                    f"self.{callee}(), whose call chain "
                    f"({' -> '.join(chain)}) waits on this actor's own "
                    f".remote() result — the actor is still busy "
                    f"running {name}(), so the chain deadlocks")
