"""jit-hygiene: impure ops and recompile hazards inside jitted bodies.

A ``jax.jit``/``tracked_jit`` body executes at *trace* time, not call
time: ``print``/``time.time``/``np.random`` run once per compile and
silently freeze their value into the program — correct-looking code
with wrong semantics, and a classic source of "why does this only log
once". Mutating attributes or globals from a traced body is the same
bug in the other direction. Unhashable static args and Python branches
on traced values are the two recompile amplifiers ``TrackedJit``
(observability/jit.py) can only *count* after the compile time is
already burned; this pass rejects them before they ship.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import (
    call_name, dotted, kwarg, literal, walk_scope,
)
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

# Call roots that are impure at trace time. jax.debug.print /
# jax.debug.callback are the sanctioned escape hatches and do not match.
_IMPURE_EXACT = {"print", "input", "breakpoint"}
_IMPURE_PREFIX = ("time.", "np.random.", "numpy.random.", "random.")

_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "bytes"}


def _is_jit_expr(node: ast.expr) -> bool:
    """Is this expression a jit transform? Covers ``jax.jit``, ``jit``,
    ``tracked_jit``, ``pjit``, ``partial(jax.jit, ...)`` and the
    factory form ``jax.jit(static_argnums=...)`` used as a decorator."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return name.rsplit(".", 1)[-1] in ("jit", "tracked_jit", "pjit")
    name = dotted(node)
    return name.rsplit(".", 1)[-1] in ("jit", "tracked_jit", "pjit")


def _static_params(fn: ast.FunctionDef,
                   jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names marked static on the wrapping jit call."""
    if jit_call is None:
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: Set[str] = set()
    names = literal(kwarg(jit_call, "static_argnames"))
    if isinstance(names, str):
        static.add(names)
    elif isinstance(names, (list, tuple)):
        static.update(n for n in names if isinstance(n, str))
    nums = literal(kwarg(jit_call, "static_argnums"))
    if isinstance(nums, int):
        nums = (nums,)
    if isinstance(nums, (list, tuple)):
        for i in nums:
            if isinstance(i, int) and 0 <= i < len(params):
                static.add(params[i])
    return static


@register
class JitHygienePass(LintPass):
    name = "jit-hygiene"
    rules = ("jit-impure-call", "jit-global-mutation",
             "jit-unhashable-static", "jit-traced-branch")
    description = ("impure ops, unhashable static args and traced-value "
                   "branching inside jax.jit/tracked_jit bodies")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        # Every def in the module, by name (methods included): call-site
        # wrapping (`self._tick = tracked_jit(self._tick_impl)`) resolves
        # through this table.
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, []).append(node)

        # (fn def, jit call or None) pairs to scan.
        jitted: List[Tuple[ast.FunctionDef, Optional[ast.Call]]] = []
        seen: Set[int] = set()

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        call = dec if isinstance(dec, ast.Call) else None
                        if id(node) not in seen:
                            seen.add(id(node))
                            jitted.append((node, call))
            elif isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                    and node.args:
                target = node.args[0]
                tname = None
                if isinstance(target, ast.Name):
                    tname = target.id
                elif isinstance(target, ast.Attribute):
                    tname = target.attr
                for fn in defs.get(tname, []):
                    if id(fn) not in seen:
                        seen.add(id(fn))
                        jitted.append((fn, node))
                # Unhashable static args are checkable even when the
                # wrapped fn lives elsewhere.
                if tname not in defs:
                    out.extend(self._check_static_hashable(
                        mod, None, node))

        for fn, call in jitted:
            out.extend(self._scan_body(mod, fn))
            out.extend(self._check_static_hashable(mod, fn, call))
            out.extend(self._check_traced_branches(mod, fn, call))
        return out

    # ------------------------------------------------------------- body

    def _scan_body(self, mod: ModuleInfo,
                   fn: ast.FunctionDef) -> Iterable[Finding]:
        # The whole subtree is traced — nested defs included (closures
        # traced inline), so do NOT skip nested scopes here.
        for node in walk_scope(fn, skip_nested=False):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in _IMPURE_EXACT or \
                        name.startswith(_IMPURE_PREFIX):
                    yield mod.finding(
                        "jit-impure-call", node,
                        f"call to {name}() inside jitted "
                        f"{fn.name}(): runs at trace time only — its "
                        f"value is frozen into the compiled program "
                        f"(use jax.debug.* or hoist it out)")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) \
                    else "nonlocal"
                yield mod.finding(
                    "jit-global-mutation", node,
                    f"{kind} statement inside jitted {fn.name}(): "
                    f"trace-time mutation escapes the compiled program")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        yield mod.finding(
                            "jit-global-mutation", node,
                            f"attribute mutation "
                            f"'{ast.unparse(t)} = ...' inside jitted "
                            f"{fn.name}(): runs once per trace, not "
                            f"per call — return the value instead")

    # ----------------------------------------------------- static args

    def _check_static_hashable(self, mod: ModuleInfo,
                               fn: Optional[ast.FunctionDef],
                               call: Optional[ast.Call]):
        if call is None:
            return
        # Unhashable literals directly in static_argnums/static_argnames
        # defaults of the wrapped fn: every call re-hashes the static
        # args, and an unhashable one raises — while a *mutable but
        # hashed-by-id* object silently recompiles per instance.
        if fn is None:
            return
        static = _static_params(fn, call)
        if not static:
            return
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        first_default = len(pos) - len(defaults)
        for i, a in enumerate(pos):
            if a.arg not in static or i < first_default:
                continue
            d = defaults[i - first_default]
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield mod.finding(
                    "jit-unhashable-static", d,
                    f"static arg {a.arg!r} of jitted {fn.name}() "
                    f"defaults to an unhashable "
                    f"{type(d).__name__.lower()} literal — jit hashes "
                    f"static args per call; use a tuple/frozen value")

    # -------------------------------------------------- traced branches

    def _check_traced_branches(self, mod: ModuleInfo, fn: ast.FunctionDef,
                               call: Optional[ast.Call]):
        """``if x > 0:`` on a traced parameter is a ConcretizationError
        at best and a per-value recompile (via forced static arg) at
        worst. Heuristic kept tight: bare non-static parameters, with
        scalar-annotated / scalar-defaulted params (static Python
        config) excluded, compared against literals with an ordering
        op."""
        static = _static_params(fn, call)
        traced: Set[str] = set()
        pos = fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        defaults = list(fn.args.defaults) + list(fn.args.kw_defaults)
        first_default = len(pos) - len(defaults)
        for i, a in enumerate(pos):
            if a.arg in static or a.arg in ("self", "cls"):
                continue
            ann = dotted(a.annotation) if a.annotation is not None else ""
            if ann.rsplit(".", 1)[-1] in _SCALAR_ANNOTATIONS:
                continue
            d = defaults[i - first_default] if i >= first_default else None
            if d is not None and isinstance(d, ast.Constant):
                continue  # scalar-config default => static Python value
            traced.add(a.arg)
        if not traced:
            return
        for node in walk_scope(fn, skip_nested=False):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            if not isinstance(test, ast.Compare) or len(test.ops) != 1:
                continue
            if not isinstance(test.ops[0],
                              (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
                continue
            sides = (test.left, test.comparators[0])
            names = [s.id for s in sides if isinstance(s, ast.Name)]
            lits = [s for s in sides if isinstance(s, ast.Constant)]
            hit = [n for n in names if n in traced]
            if hit and lits:
                yield mod.finding(
                    "jit-traced-branch", node,
                    f"Python branch on traced argument {hit[0]!r} "
                    f"inside jitted {fn.name}(): concretizes the "
                    f"tracer (or forces a per-value recompile) — use "
                    f"lax.cond/jnp.where, or mark the arg static")
