"""await-atomicity: a check of ``self`` state invalidated across an await.

Every ``await`` (and each ``async for`` iteration / ``async with``
entry) is a point where the event loop may run *other* coroutines of
the same object — an async actor with ``max_concurrency > 1``, a
controller serving several RPCs, a background task beside a request
path. The async TOCTOU this pass hunts:

    if latest <= self._version:      # check
        return
    weights = await store.fetch()    # yield point: anyone can run
    self._version = latest           # act on a stale check

Between the check and the act another coroutine may have moved
``self._version`` forward; the act then clobbers newer state. The fix
is either an ``asyncio.Lock`` held across both sides or re-checking
after the await (``while self._pending: self._pending.pop(0)`` is the
clean idiom — each loop-head test is a *fresh* check).

Mechanics: a worklist analysis over the function CFG. Branch tests
and asserts reading ``self.<attr>`` open a check record carrying the
lockset held at the test (lexical ``with``/``async with`` plus
explicit ``.acquire()`` tracked through the CFG). Any yield point
marks live records crossed. A statement that may modify the attr —
direct store, subscript/field store, mutating container method, or a
one-hop ``self.m()`` call whose body writes it — fires when a crossed
record exists and no lock is shared between check and act. Two
precision guards keep the pass quiet on healthy code: re-reading the
attr in a later test replaces the record (strong update), so
re-check-after-await never fires; and a check only pairs with acts it
*controls* — inside its construct, or anywhere after it when the
guarded branch exits early (``if stale: return`` / ``continue``) or
the test heads a spin-wait loop. Only attrs touched by more than one
method of the class are tracked: an attr private to one coroutine
body cannot be invalidated behind its back (precision over recall).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Set, Tuple

from ray_tpu._private.lint._ast_util import enclosing_class_map
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import (
    cfgs_for_module, held_locksets, lexical_locks, solve, yield_points,
)
from ray_tpu._private.lint.race import (
    fn_self_accesses, fn_self_writes, stmt_self_calls, stmt_self_reads,
    stmt_self_writes,
)

# One check record: (lockset at the check, crossed a yield point yet,
# line of the check, last line the check still guards).
_Rec = Tuple[FrozenSet[str], bool, int, int]

_INITISH = {"__init__", "__new__", "__post_init__"}

_EXITS = (ast.Return, ast.Raise, ast.Break, ast.Continue)


def _guard_ranges(fn) -> Dict[int, Tuple[int, int]]:
    """id(test node) -> (first, last) line the test *guards*: a check
    only pairs with acts it actually controls. For an ``if``/``while``
    that is its construct's extent; when a branch ends in
    return/raise/break/continue (the early-exit guard idiom) or the
    statement is an ``assert``, everything to the end of the function
    is control-dependent on the test having passed."""
    out: Dict[int, Tuple[int, int]] = {}
    fn_end = getattr(fn, "end_lineno", 10 ** 9) or 10 ** 9
    for n in ast.walk(fn):
        if isinstance(n, (ast.If, ast.While)):
            exits = any(
                branch and isinstance(branch[-1], _EXITS)
                for branch in (n.body, n.orelse))
            hi = fn_end if (exits or isinstance(n, ast.While)) \
                else (getattr(n, "end_lineno", fn_end) or fn_end)
            out[id(n.test)] = (n.test.lineno, hi)
        elif isinstance(n, ast.Assert):
            out[id(n)] = (n.lineno, fn_end)
    return out


def _join(a: Dict[str, FrozenSet[_Rec]],
          b: Dict[str, FrozenSet[_Rec]]) -> Dict[str, FrozenSet[_Rec]]:
    out = dict(a)
    for attr, recs in b.items():
        out[attr] = out.get(attr, frozenset()) | recs
    return out


@register
class AwaitAtomicityPass(LintPass):
    name = "await-atomicity"
    rules = ("await-atomicity",)
    description = ("self.<attr> check-then-act spanning an await in "
                   "async methods with no lock held across both sides")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        owner, _classes = enclosing_class_map(mod.tree)
        cfgs = cfgs_for_module(mod)

        # Per class: what each method writes (one-hop call expansion)
        # and which attrs more than one method touches.
        writes_by_cls: Dict[str, Dict[str, Set[str]]] = {}
        touchers: Dict[str, Dict[str, Set[str]]] = {}
        for fn, cls in owner.items():
            if not cls:
                continue
            writes_by_cls.setdefault(cls, {}).setdefault(
                fn.name, set()).update(fn_self_writes(fn))
            if fn.name not in _INITISH:
                for attr in fn_self_accesses(fn):
                    touchers.setdefault(cls, {}).setdefault(
                        attr, set()).add(fn.name)

        for fn, cls in owner.items():
            if not cls or not isinstance(fn, ast.AsyncFunctionDef):
                continue
            cfg = cfgs.get(fn)
            if cfg is None:
                continue
            shared = {attr for attr, who in
                      touchers.get(cls, {}).items()
                      if who - {fn.name}}
            if not shared:
                continue
            out.extend(self._check_fn(
                mod, fn, cfg, writes_by_cls.get(cls, {}), shared))
        return out

    def _check_fn(self, mod: ModuleInfo, fn, cfg, cls_writes, shared):
        lex = lexical_locks(fn)
        held = held_locksets(cfg)
        guards = _guard_ranges(fn)

        def locks_at(stmt) -> FrozenSet[str]:
            return (lex.get(id(stmt), frozenset())
                    | held.get(id(stmt), frozenset()))

        hits: Dict[Tuple[str, int, int], ast.AST] = {}

        def transfer(block, state):
            st = dict(state)
            for stmt in block.stmts:
                # Awaits evaluate before the statement's store takes
                # effect (``self.x = await f()``), so mark first.
                if yield_points(stmt):
                    for attr, recs in list(st.items()):
                        st[attr] = frozenset(
                            (lk, True, ln, hi) for lk, _c, ln, hi in recs)
                written = stmt_self_writes(stmt) & shared
                for m in stmt_self_calls(stmt):
                    written |= cls_writes.get(m, set()) & shared
                if written:
                    wlocks = locks_at(stmt)
                    wline = getattr(stmt, "lineno", 0)
                    for attr in written:
                        for lk, crossed, ln, hi in st.pop(
                                attr, frozenset()):
                            if crossed and not (lk & wlocks) \
                                    and ln <= wline <= hi:
                                hits.setdefault((attr, ln, wline), stmt)
                if isinstance(stmt, (ast.expr, ast.Assert)):
                    ln = getattr(stmt, "lineno", 0)
                    _lo, hi = guards.get(
                        id(stmt), (ln, 10 ** 9))
                    for attr in stmt_self_reads(stmt) & shared:
                        st[attr] = frozenset({
                            (locks_at(stmt), False, ln, hi)})
            return st

        solve(cfg, transfer, {}, _join)

        for (attr, check_ln, _act_ln), stmt in sorted(
                hits.items(), key=lambda kv: kv[0]):
            yield mod.finding(
                "await-atomicity", stmt,
                f"self.{attr} checked at line {check_ln} and modified "
                f"here, with an await in between and no common lock: "
                f"another coroutine of {fn.name}()'s object can run at "
                f"the yield point and invalidate the check — hold an "
                f"asyncio.Lock across check-and-act, or re-check after "
                f"the await")
