"""control-loop: hygiene for metrics-driven control loops.

PR 7's control plane (serve autoscaler, data backpressure tuner, memory
preemption) is a set of periodic policy loops. The failure modes are
quiet and fleet-wide: a loop with no sleep pegs a core; a constant
period synchronizes every process in the cluster into thundering-herd
metric fetches; a policy coroutine called without ``await`` (or without
handing it to a task spawner) silently never runs, and the cluster just
stops adapting.

Scope: only functions whose NAME says they are control-plane code
(``policy`` / ``autoscal`` / ``backpressure`` / ``preempt`` / ``ctrl``
/ ``control``). General-purpose loops (heartbeats, reapers, reconcile)
have their own conventions and stay out of scope.

Three rules:

- ``ctrl-busy-spin``: an unbounded ``while`` loop in a control function
  with no sleep/wait anywhere in its test or body.
- ``ctrl-unjittered-period``: the loop's sleep/wait period is a bare
  numeric literal — every process wakes on the same beat; multiply by a
  jitter term (e.g. ``random.uniform(0.8, 1.2)``).
- ``ctrl-unawaited-policy``: a call to a module-local ``async def``
  control function that is neither awaited nor consumed by another call
  (``spawn_task(...)`` / ``create_task(...)``) — the coroutine object
  is dropped and the policy never executes.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from ray_tpu._private.lint._ast_util import (
    awaited_calls, call_name, consumed_calls, walk_scope,
)
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

_CTRL_NAME = re.compile(
    r"policy|autoscal|backpressure|preempt|ctrl|control")

# Callable suffixes that bound a loop iteration in time. ``.wait`` covers
# both threading.Event.wait(timeout) (the sync-loop idiom) and
# asyncio waits; ``.get``/``.join`` cover queue-driven loops.
_SLEEPISH_EXACT = ("time.sleep", "asyncio.sleep")
_SLEEPISH_SUFFIX = (".sleep", ".wait", ".wait_for", ".get", ".join",
                    ".select", ".poll")


def _is_sleepish(name: Optional[str]) -> bool:
    if not name:
        return False
    return name in _SLEEPISH_EXACT or name.endswith(_SLEEPISH_SUFFIX)


def _is_unbounded(loop: ast.While) -> bool:
    """while True / while not <flag>: the shapes daemon loops take."""
    test = loop.test
    if isinstance(test, ast.Constant) and test.value is True:
        return True
    return isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)


def _constant_period(call: ast.Call) -> bool:
    """First positional arg (or timeout= kwarg) is a bare number —
    a fixed, fleet-synchronized period."""
    args = list(call.args)
    for kw in call.keywords:
        if kw.arg in ("timeout", "delay"):
            args.append(kw.value)
    return bool(args) and isinstance(args[0], ast.Constant) \
        and isinstance(args[0].value, (int, float))


@register
class ControlLoopPass(LintPass):
    name = "control-loop"
    rules = ("ctrl-busy-spin", "ctrl-unjittered-period",
             "ctrl-unawaited-policy")
    description = ("control-plane loop hygiene: bounded jittered "
                   "periods, no dropped policy coroutines")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        awaited = awaited_calls(mod.tree)
        consumed = consumed_calls(mod.tree)
        async_ctrl = {
            n.name for n in ast.walk(mod.tree)
            if isinstance(n, ast.AsyncFunctionDef)
            and _CTRL_NAME.search(n.name)
        }
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            in_ctrl = bool(_CTRL_NAME.search(fn.name))
            for sub in walk_scope(fn, skip_nested=True):
                if in_ctrl and isinstance(sub, ast.While) \
                        and _is_unbounded(sub):
                    out.extend(self._check_loop(mod, fn, sub))
                # Dropped policy coroutine: module-local async control
                # fn called bare — not awaited, not fed to a spawner.
                if isinstance(sub, ast.Call) and id(sub) not in awaited \
                        and id(sub) not in consumed:
                    name = call_name(sub)
                    leaf = (name or "").rsplit(".", 1)[-1]
                    if leaf in async_ctrl:
                        out.append(mod.finding(
                            "ctrl-unawaited-policy", sub,
                            f"{name}() builds a coroutine and drops it "
                            f"— the policy never runs; 'await' it or "
                            f"hand it to spawn_task()/create_task()"))
        return out

    def _check_loop(self, mod: ModuleInfo, fn, loop: ast.While
                    ) -> Iterable[Finding]:
        sleeps: List[ast.Call] = []
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) and \
                    _is_sleepish(call_name(node)):
                sleeps.append(node)
        if not sleeps:
            yield mod.finding(
                "ctrl-busy-spin", loop,
                f"unbounded control loop in '{fn.name}' with no sleep/"
                f"wait — pegs a core and hammers the metrics plane; "
                f"bound the period")
            return
        for call in sleeps:
            if _constant_period(call):
                yield mod.finding(
                    "ctrl-unjittered-period", call,
                    f"constant period in control loop '{fn.name}' "
                    f"synchronizes every process onto the same beat — "
                    f"multiply by a jitter term (random.uniform)")
