"""distributed-deadlock: self-waits and unbounded waits in remote bodies.

The two wedge shapes the cluster forensics plane (PR 4/5) keeps
attributing after the fact:

- ``deadlock-self-get``: ``ray_tpu.get(self.<m>.remote(...))`` inside
  an actor method. An actor executes one method at a time; getting the
  result of a call *to itself* waits on work that can only start after
  the current method returns — a guaranteed single-actor deadlock.
  Simple ref-through-local flows (``r = self.m.remote(); ...
  ray_tpu.get(r)``) are tracked too.
- ``deadlock-unbounded-wait``: ``.call()`` / ``.acall()`` / bare
  ``.wait()`` / ``.result()`` / ``.join()`` with no timeout inside a
  remote body. Cross-worker RPCs without a bound turn one lost peer
  into a wedged actor that the lease reaper then can't distinguish
  from a long-running task.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ray_tpu._private.lint._ast_util import (
    awaited_calls, call_name, consumed_calls, dotted, has_timeout,
    walk_scope,
)
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

_GET_ROOTS = ("ray_tpu", "ray")
_WAITISH = (".call", ".acall", ".wait", ".result", ".join")


def _is_remote_decorated(node) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if dotted(target).rsplit(".", 1)[-1] == "remote":
            return True
    return False


def _is_get_call(call: ast.Call) -> bool:
    name = call_name(call)
    return (name.endswith(".get")
            and name.rsplit(".", 1)[0].rsplit(".", 1)[-1] in _GET_ROOTS)


def _self_remote_call(node: ast.AST) -> bool:
    """Does this expression subtree contain ``self.<m>.remote(...)``?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name.startswith("self.") and name.endswith(".remote"):
                return True
    return False


@register
class DeadlockPass(LintPass):
    name = "distributed-deadlock"
    rules = ("deadlock-self-get", "deadlock-unbounded-wait")
    description = ("self-gets and unbounded cross-worker waits inside "
                   "@remote task/actor bodies")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        bodies: List[ast.AST] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_remote_decorated(node):
                bodies.append(node)
            elif isinstance(node, ast.ClassDef) \
                    and _is_remote_decorated(node):
                bodies.extend(
                    c for c in node.body
                    if isinstance(c, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)))
        awaited = awaited_calls(mod.tree) | consumed_calls(mod.tree)
        for fn in bodies:
            out.extend(self._scan(mod, fn, awaited))
        return out

    def _scan(self, mod: ModuleInfo, fn, awaited: Set[int]):
        # Locals assigned from self.<m>.remote(...) — refs whose get()
        # is a self-wait even when it happens lines later.
        self_refs: Set[str] = set()
        for node in walk_scope(fn, skip_nested=True):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, (ast.Call, ast.List,
                                            ast.Tuple)) and \
                    _self_remote_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self_refs.add(t.id)

        for node in walk_scope(fn, skip_nested=True):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if _is_get_call(node):
                direct = any(_self_remote_call(a) for a in node.args)
                via_ref = any(
                    isinstance(a, ast.Name) and a.id in self_refs
                    for a in node.args) or any(
                    isinstance(e, ast.Name) and e.id in self_refs
                    for a in node.args if isinstance(a, (ast.List,
                                                         ast.Tuple))
                    for e in a.elts)
                if direct or via_ref:
                    yield mod.finding(
                        "deadlock-self-get", node,
                        f"{name}() on this actor's own .remote() "
                        f"result inside {fn.name}(): the actor runs "
                        f"one method at a time, so it waits on work "
                        f"that can only start after this method "
                        f"returns — guaranteed deadlock")
                continue
            if "." in name and name.endswith(_WAITISH) \
                    and id(node) not in awaited \
                    and not node.args and not has_timeout(node):
                # Zero positional args also exempts str.join(iterable)
                # and friends — everything blocking here takes its
                # bound positionally.
                yield mod.finding(
                    "deadlock-unbounded-wait", node,
                    f"unbounded {name}() inside remote body "
                    f"{fn.name}(): a lost peer wedges this "
                    f"worker forever — pass a timeout and handle it")
