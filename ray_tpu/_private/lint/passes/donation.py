"""donation-use-after: reads of a binding after it flowed into a
donated jit position.

``donate_argnums`` hands the argument's HBM to XLA for reuse: after the
call returns, the old buffer may already hold activations of the *next*
step.  Reading the donated binding afterwards is not an error anywhere
— on CPU backends it "works", under ``jit`` tracing it sometimes works,
on a TPU pod it silently reads freed HBM.  That makes it the perfect
lint target: trivially fatal, invisible to tests off-pod.

The pass runs a may-analysis over the per-function CFG: a binding that
flowed into a donated position *on some path* is poisoned until rebound,
and any later read (including attribute reads ``state.params`` and
writes into its fields ``state.field = x``) is a finding.  Donating
callables are recognized three ways:

- names assigned a ``jax.jit`` / ``pjit`` / ``tracked_jit`` result with
  a literal ``donate_argnums`` in any lexically enclosing scope
  (``fn = jax.jit(step, donate_argnums=(0,)); fn(state, batch)``);
- ``self.X`` attributes assigned such a result anywhere in the class
  (the serve engine's ``self._jit_tick`` pattern: wrapped in
  ``__init__``, called in ``step()``);
- one level of interprocedural summary: a function whose *parameter*
  flows into a donated position poisons its callers' arguments too
  (resolved through the package call graph, ambiguity → silence).

The donating call itself is exempt (``state = fn(state, batch)``
reads then rebinds ``state`` — the idiom the API wants), as is any
path where the name is rebound before the read.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name, dotted, literal
from ray_tpu._private.lint.callgraph import (
    CallGraph, FuncInfo, get_call_graph,
)
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import (
    bound_names, cfgs_for_module, deleted_names, effective_exprs, solve,
    walk_no_scope,
)

_JIT_TAILS = {"jit", "pjit", "tracked_jit"}


def donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions of a jit-family wrap call with a literal
    ``donate_argnums``, else None."""
    if call_name(call).rsplit(".", 1)[-1] not in _JIT_TAILS:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            val = literal(kw.value)
            if isinstance(val, int):
                return (val,)
            if isinstance(val, (tuple, list)) and val and all(
                    isinstance(v, int) for v in val):
                return tuple(val)
    return None


def _pure_dotted(expr: ast.expr) -> Optional[str]:
    """"a.b.c" for a Name/Attribute chain of plain names, else None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _pure_dotted(expr.value)
        return f"{base}.{expr.attr}" if base else None
    return None


class _ModuleMaps:
    """Where donating callables live in one module: per-scope names and
    per-class ``self.X`` attributes."""

    def __init__(self, mod: ModuleInfo):
        # scope key: id(enclosing function node), or None at module level
        self.scoped: Dict[Optional[int], Dict[str, Tuple[int, ...]]] = {}
        self.class_attr: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        self._index(mod.tree, None, "")

    def _index(self, node: ast.AST, scope: Optional[int],
               cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._index(child, scope, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._index(child, id(child), cls)
            else:
                if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call):
                    pos = donated_positions(child.value)
                    if pos is not None:
                        self._record(child.targets, pos, scope, cls)
                self._index(child, scope, cls)

    def _record(self, targets, pos, scope, cls) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                prev = self.scoped.setdefault(scope, {}).get(t.id, ())
                self.scoped[scope][t.id] = tuple(sorted(set(prev)
                                                        | set(pos)))
            elif isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id == "self" and cls:
                attrs = self.class_attr.setdefault(cls, {})
                prev = attrs.get(t.attr, ())
                attrs[t.attr] = tuple(sorted(set(prev) | set(pos)))


@register
class DonationPass(LintPass):
    name = "donation-use-after"
    rules = ("donation-use-after",)
    description = ("no reads of a binding after it flowed into a "
                   "donate_argnums position on some path: donated HBM "
                   "is XLA's to reuse, so the read returns garbage on "
                   "a real TPU")

    def __init__(self):
        self._mods: List[ModuleInfo] = []

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        graph = get_call_graph(self._mods)
        maps = {m.relpath: _ModuleMaps(m) for m in self._mods}
        summaries = self._summaries(graph, maps)
        out: List[Finding] = []
        for mod in self._mods:
            if "donate_argnums" not in mod.src and not summaries:
                continue
            out.extend(self._check_module(mod, graph, maps, summaries))
        return out

    # ------------------------------------------------ callable lookup

    def _call_positions(self, call: ast.Call, fi: Optional[FuncInfo],
                        mod: ModuleInfo, graph: CallGraph,
                        maps: Dict[str, _ModuleMaps],
                        summaries: Dict[int, Set[int]],
                        ) -> List[Tuple[int, int]]:
        """(donated-position-in-callee, call-arg-index) pairs for this
        call site."""
        mm = maps[mod.relpath]
        func = call.func
        # jax.jit(f, donate_argnums=...)(args): wrap applied in place.
        if isinstance(func, ast.Call):
            pos = donated_positions(func)
            if pos is not None:
                return [(p, p) for p in pos]
        if isinstance(func, ast.Name):
            scope_chain: List[Optional[int]] = []
            f = fi
            while f is not None:
                scope_chain.append(id(f.node))
                f = f.parent
            scope_chain.append(None)
            for scope in scope_chain:
                pos = mm.scoped.get(scope, {}).get(func.id)
                if pos is not None:
                    return [(p, p) for p in pos]
        elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name) and func.value.id in (
                    "self", "cls") and fi is not None and fi.cls:
            pos = mm.class_attr.get(fi.cls, {}).get(func.attr)
            if pos is not None:
                return [(p, p) for p in pos]
        # One-level summary through the call graph.
        callee = graph.resolve(func, fi, mod)
        if callee is not None and id(callee.node) in summaries:
            shift = 0
            if callee.cls and isinstance(func, ast.Attribute):
                params = callee.node.args.args
                if params and params[0].arg in ("self", "cls"):
                    shift = 1
            return [(p, p - shift)
                    for p in summaries[id(callee.node)]
                    if p - shift >= 0]
        return []

    def _summaries(self, graph: CallGraph,
                   maps: Dict[str, _ModuleMaps]) -> Dict[int, Set[int]]:
        """id(func node) → parameter indices the function donates
        (one level: param flows directly into a donated position of a
        locally-known donating callable)."""
        out: Dict[int, Set[int]] = {}
        for fi in graph.funcs:
            args = fi.node.args
            params = [a.arg for a in args.posonlyargs + args.args]
            if not params:
                continue
            for call, _callee in graph.direct_calls(fi):
                for pos, argidx in self._call_positions(
                        call, fi, fi.mod, graph, maps, {}):
                    if argidx >= len(call.args):
                        continue
                    arg = call.args[argidx]
                    if any(isinstance(a, ast.Starred)
                           for a in call.args[:argidx + 1]):
                        continue
                    if isinstance(arg, ast.Name) and arg.id in params:
                        out.setdefault(id(fi.node), set()).add(
                            params.index(arg.id))
        return out

    # -------------------------------------------------------- analysis

    def _check_module(self, mod: ModuleInfo, graph: CallGraph,
                      maps: Dict[str, _ModuleMaps],
                      summaries: Dict[int, Set[int]],
                      ) -> Iterable[Finding]:
        for fn, cfg in cfgs_for_module(mod).items():
            fi = graph.by_node.get(id(fn))
            yield from self._check_function(fn, cfg, fi, mod, graph,
                                            maps, summaries)

    def _check_function(self, fn, cfg, fi, mod, graph, maps,
                        summaries) -> Iterable[Finding]:
        State = Dict[str, FrozenSet[int]]     # dotted name → donation lines
        reported: Dict[Tuple[int, str, int], Tuple[ast.AST, str, int]] = {}

        def join(a: State, b: State) -> State:
            out = dict(a)
            for k, v in b.items():
                out[k] = out.get(k, frozenset()) | v
            return out

        def loads_of(stmt: ast.AST) -> List[Tuple[str, ast.AST]]:
            exprs = list(effective_exprs(stmt))
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                # ``state.field = x`` / ``d[k] = x`` read their base.
                exprs += [t for t in targets
                          if not isinstance(t, ast.Name)]
            out: List[Tuple[str, ast.AST]] = []
            for e in exprs:
                for n in walk_no_scope(e):
                    if isinstance(n, ast.Name) and isinstance(
                            n.ctx, ast.Load):
                        out.append((n.id, n))
                    elif isinstance(n, ast.Attribute) and isinstance(
                            n.ctx, ast.Load):
                        d = _pure_dotted(n)
                        if d is not None:
                            out.append((d, n))
            if isinstance(stmt, ast.AugAssign):
                d = _pure_dotted(stmt.target)
                if d is not None:
                    out.append((d, stmt.target))
            return out

        def transfer(block, st: State) -> State:
            st = dict(st)
            for stmt in block.stmts:
                # 1. Reads checked against the incoming poison set.
                for name, node in loads_of(stmt):
                    for key, lines in st.items():
                        if name == key or name.startswith(key + "."):
                            for ln in lines:
                                rk = (getattr(node, "lineno", 0), key, ln)
                                reported.setdefault(rk, (node, key, ln))
                # 2. New donations from calls in this statement.
                for e in effective_exprs(stmt):
                    for n in walk_no_scope(e):
                        if not isinstance(n, ast.Call):
                            continue
                        for pos, argidx in self._call_positions(
                                n, fi, mod, graph, maps, summaries):
                            if argidx >= len(n.args):
                                continue
                            if any(isinstance(a, ast.Starred)
                                   for a in n.args[:argidx + 1]):
                                continue
                            d = _pure_dotted(n.args[argidx])
                            if d is not None:
                                st[d] = st.get(d, frozenset()) \
                                    | frozenset([n.lineno])
                # 3. Rebinds clear the poison.
                kills = set(bound_names(stmt)) | set(deleted_names(stmt))
                if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = stmt.targets if isinstance(
                        stmt, ast.Assign) else [stmt.target]
                    flat: List[ast.expr] = []
                    while targets:
                        t = targets.pop()
                        if isinstance(t, (ast.Tuple, ast.List)):
                            targets.extend(t.elts)
                        elif isinstance(t, ast.Starred):
                            targets.append(t.value)
                        else:
                            flat.append(t)
                    for t in flat:
                        d = _pure_dotted(t)
                        if d is not None:
                            kills.add(d)
                if kills:
                    for key in list(st):
                        if key in kills or any(
                                key.startswith(k + ".") for k in kills):
                            del st[key]
            return st

        solve(cfg, transfer, {}, join, follow_exc=False)
        for node, key, donate_line in reported.values():
            yield mod.finding(
                "donation-use-after", node,
                f"'{key}' is read in {fn.name}() after flowing into a "
                f"donate_argnums position at line {donate_line}: the "
                f"buffer belongs to XLA once donated and may already "
                f"be reused, so this read returns garbage on TPU — "
                f"rebind the name from the call's result or drop the "
                f"donation")
