"""jit-tracking: hot-path programs must compile through tracked_jit.

The XLA attribution plane (observability/xla.py) only sees programs
that compile through :func:`ray_tpu.observability.tracked_jit` — a raw
``jax.jit(...)`` in a hot-path package is a program with no trace
counters, no cost/memory analysis row, no MFU/MBU, and no regression
sentinel: invisible to every "which program is eating the fleet?"
question the plane answers. This pass rejects raw jit in the packages
whose programs the plane is meant to cover (``serve/``, ``train/``,
``rllib/``, ``parallel/``); deliberately untracked programs take the
standard inline suppression (``# graftlint: disable=jit-untracked``).
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Set

from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)

# Path segments of the packages whose jitted programs the attribution
# plane must see. Everything else (observability itself, util, tests)
# may use raw jax.jit freely.
_HOT_PACKAGES = {"serve", "train", "rllib", "parallel"}

# Fixture twins live under tests/lint_fixtures/, outside the hot
# packages; scope them in by basename so the rule-set test can drive
# the pass against them.
_FIXTURE_PREFIX = "jit_untracked"


def _in_scope(relpath: str) -> bool:
    parts = relpath.replace(os.sep, "/").split("/")
    if os.path.basename(relpath).startswith(_FIXTURE_PREFIX):
        return True
    return any(p in _HOT_PACKAGES for p in parts)


def _jax_aliases(tree: ast.Module) -> Set[str]:
    """Names the ``jax`` module is imported as."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax":
                    out.add(alias.asname or "jax")
    return out


def _jit_names(tree: ast.Module) -> Set[str]:
    """Bare names bound to ``jax.jit`` (``from jax import jit [as j]``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    out.add(alias.asname or "jit")
    return out


@register
class JitTrackingPass(LintPass):
    name = "jit-tracking"
    rules = ("jit-untracked",)
    description = ("raw jax.jit in hot-path packages (serve/train/"
                   "rllib/parallel) must route through tracked_jit so "
                   "the XLA attribution plane sees the program")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not _in_scope(mod.relpath):
            return []
        jax_aliases = _jax_aliases(mod.tree)
        jit_names = _jit_names(mod.tree)
        if not jax_aliases and not jit_names:
            return []

        def is_raw_jit_ref(node: ast.expr) -> bool:
            if isinstance(node, ast.Attribute) and node.attr == "jit":
                base = node.value
                return isinstance(base, ast.Name) and \
                    base.id in jax_aliases
            if isinstance(node, ast.Name):
                return node.id in jit_names
            return False

        def is_partial_jit(node: ast.expr) -> bool:
            # partial(jax.jit, ...) — the factory form.
            if not isinstance(node, ast.Call):
                return False
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            return fname == "partial" and bool(node.args) and \
                is_raw_jit_ref(node.args[0])

        out: List[Finding] = []

        def flag(node: ast.AST, form: str) -> None:
            out.append(mod.finding(
                "jit-untracked", node,
                f"raw {form} in hot-path package: programs compiled "
                f"here are invisible to the XLA attribution plane "
                f"(no cost row, MFU/MBU, or regression sentinel) — "
                f"use ray_tpu.observability.tracked_jit, or suppress "
                f"a deliberately untracked program inline"))

        # partial(jax.jit, ...) nodes already reported through the call
        # applying them — don't double-flag the inner factory.
        applied: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and is_partial_jit(node.func):
                applied.add(id(node.func))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                # jax.jit(f, ...) / jit(f, ...) — including the
                # factory-then-apply partial(jax.jit, ...)(f).
                if is_raw_jit_ref(node.func):
                    flag(node, "jax.jit(...) call")
                elif is_partial_jit(node.func):
                    flag(node, "partial(jax.jit, ...)(...) call")
                elif is_partial_jit(node) and id(node) not in applied:
                    # Bare partial(jax.jit, ...) used as a decorator or
                    # stored factory: the jit still compiles untracked.
                    flag(node, "partial(jax.jit, ...) factory")
            elif isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if is_raw_jit_ref(dec):
                        flag(dec, "@jax.jit decorator")
        return out
