"""lock-discipline: the ``with self._lock`` acquisition graph.

Builds a cross-module graph of lock acquisitions (``with``/``async
with`` on any lock-named context manager) and flags:

- ``lock-cycle``: lock A held while acquiring B somewhere, and B held
  while acquiring A somewhere else — the classic two-thread deadlock
  that only fires under production interleavings. Edges also follow
  one level of ``self.method()`` calls, so a helper that grabs a lock
  is charged to its holding caller.
- ``lock-blocking-call``: a blocking call (sleep, sync subprocess,
  sync socket/RPC) while holding a lock. Everything else queueing on
  that lock — often the metrics flusher or a heartbeat — stalls for
  the call's full duration.

Lock identity is ``ClassName.attr`` for ``self.X`` locks (every
instance of the class shares the ordering discipline) and
``module:NAME`` for module-level locks.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name, walk_scope
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

_BLOCKING_EXACT = {
    "time.sleep", "os.system", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
}
_BLOCKING_SUFFIX = (".sendall", ".recv", ".accept", ".call")


def _lockish(expr: ast.expr) -> Optional[str]:
    """Unparse of a lock-looking context expr, else None."""
    try:
        text = ast.unparse(expr)
    except Exception:
        return None
    base = text.split("(")[0]
    if "lock" in base.lower() or "mutex" in base.lower():
        return base
    return None


@register
class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    rules = ("lock-cycle", "lock-blocking-call")
    description = ("cycles in the lock-acquisition graph and blocking "
                   "calls made while holding a lock")

    def __init__(self):
        # (holder, acquired) -> first observed (mod, line)
        self._edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    # ------------------------------------------------------------ names

    def _lock_id(self, text: str, cls: str, mod: ModuleInfo) -> str:
        if text.startswith("self."):
            owner = cls or mod.relpath
            return f"{owner}.{text[5:]}"
        if "." not in text:
            return f"{mod.relpath}:{text}"
        return text

    # ------------------------------------------------------------- scan

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        # method -> locks it acquires directly, per class (one level of
        # self-call expansion below).
        method_locks: Dict[Tuple[str, str], Set[str]] = {}
        # (class, fn, with-node) worklist with held-lock context.
        ctx: List[Tuple[str, ast.AST]] = []

        def owner_class(path: List[ast.AST]) -> str:
            for n in reversed(path):
                if isinstance(n, ast.ClassDef):
                    return n.name
            return ""

        def visit(node: ast.AST, path: List[ast.AST]):
            for child in ast.iter_child_nodes(node):
                visit(child, path + [node])

        # Collect per-function lock info with an explicit walk that
        # remembers the enclosing class and function.
        def walk_fn(fn, cls: str):
            held_stack: List[Tuple[str, ast.AST]] = []

            def rec(node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    names = []
                    for item in node.items:
                        text = _lockish(item.context_expr)
                        if text is not None:
                            names.append(
                                self._lock_id(text, cls, mod))
                    for name in names:
                        for held, _ in held_stack:
                            if held != name:
                                self._edges.setdefault(
                                    (held, name),
                                    (mod.relpath, node.lineno,
                                     mod.context_for(node.lineno)))
                        method_locks.setdefault(
                            (cls, fn.name), set()).add(name)
                    for name in names:
                        held_stack.append((name, node))
                    for child in node.body:
                        rec(child)
                    for _ in names:
                        held_stack.pop()
                    return
                if isinstance(node, ast.Call) and held_stack:
                    name = call_name(node)
                    blocking = name in _BLOCKING_EXACT or (
                        "." in name
                        and name.endswith(_BLOCKING_SUFFIX)
                        and not name.endswith(".acall"))
                    if blocking:
                        held = held_stack[-1][0]
                        out.append(mod.finding(
                            "lock-blocking-call", node,
                            f"{name}() while holding {held}: every "
                            f"other thread queueing on the lock "
                            f"stalls for the call's full duration"))
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    return  # nested scope: runs elsewhere/later
                # Record self-calls made under a held lock for the
                # one-level expansion.
                if isinstance(node, ast.Call) and held_stack:
                    name = call_name(node)
                    if name.startswith("self.") and name.count(".") == 1:
                        for held, _ in held_stack:
                            calls_under.setdefault(
                                (cls, name[5:]), set()).add(
                                (held, mod.relpath, node.lineno))
                for child in ast.iter_child_nodes(node):
                    rec(child)

            for child in fn.body:
                rec(child)

        calls_under: Dict[Tuple[str, str], Set[Tuple[str, str, int]]] = {}

        def scan(node, cls: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    walk_fn(child, cls)
                    scan(child, cls)
                else:
                    scan(child, cls)

        scan(mod.tree, "")

        # One-level expansion: caller holds L and calls self.m();
        # m directly acquires L' -> edge L -> L'.
        for (cls, meth), sites in calls_under.items():
            for acquired in method_locks.get((cls, meth), ()):
                for held, relpath, line in sites:
                    if held != acquired:
                        self._edges.setdefault(
                            (held, acquired),
                            (relpath, line, ""))
        return out

    # --------------------------------------------------------- finalize

    def finalize(self) -> Iterable[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, set()).add(b)
        reported: Set[Tuple[str, ...]] = set()
        for (a, b), (path, line, context) in sorted(self._edges.items()):
            # Cycle check: can we get from b back to a?
            stack, seen = [b], set()
            found = False
            while stack:
                n = stack.pop()
                if n == a:
                    found = True
                    break
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(graph.get(n, ()))
            if not found:
                continue
            key = tuple(sorted((a, b)))
            if key in reported:
                continue
            reported.add(key)
            yield Finding(
                rule="lock-cycle", path=path, line=line,
                message=(f"lock-order cycle: {a} is held while "
                         f"acquiring {b} here, and {b} is (transitively) "
                         f"held while acquiring {a} elsewhere — two "
                         f"threads taking opposite orders deadlock"),
                context=context)
