"""event-schema: cluster-event emission sites vs. the registry vs. docs.

Migrated from the PR-4 test-side lint (tests/test_failure_forensics.py
``TestEventLint``): every event type emitted anywhere in the package
must be registered in ``observability/events.py``; every registered
type must have at least one emission site (dead schema entries mislead
postmortems); and every registered type must be documented in the
dashboard ``GET /api/events`` table (``dashboard/head.py`` module
docstring).

The registry is read *statically* (AST of the events module inside the
linted tree), so the pass works on fixture trees and never imports the
code under analysis. Trees without an ``observability/events.py`` are
exempt — the schema doesn't apply to them.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)

_EMIT_RE = re.compile(
    r"""(?:_record_event\(\s*|_report_event\(\s*|
        event_type\s*=\s*)["']([A-Z][A-Z_]+)["']""", re.VERBOSE)


def _registry_keys(tree: ast.Module) -> Optional[Dict[str, int]]:
    """{event type: line} from the ``EVENT_TYPES = {...}`` literal."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name) and t.id == "EVENT_TYPES"
                       for t in targets):
                continue
            if isinstance(node.value, ast.Dict):
                out = {}
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        out[k.value] = k.lineno
                return out
    return None


@register
class EventSchemaPass(LintPass):
    name = "event-schema"
    rules = ("event-unregistered-emit", "event-dead-type",
             "event-undocumented-type")
    description = ("cluster-event emission sites ⊆ registry ⊆ dashboard "
                   "docs (ex tests/test_failure_forensics TestEventLint)")

    def __init__(self):
        self._emitted: Dict[str, List[Finding]] = {}
        self._registry: Optional[Dict[str, int]] = None
        self._registry_mod: Optional[ModuleInfo] = None
        self._dashboard_doc: Optional[str] = None
        self._dashboard_mod: Optional[ModuleInfo] = None

    def check_module(self, mod: ModuleInfo):
        if mod.relpath.endswith("observability/events.py"):
            self._registry = _registry_keys(mod.tree)
            self._registry_mod = mod
        if mod.relpath.endswith("dashboard/head.py"):
            self._dashboard_doc = ast.get_docstring(mod.tree) or ""
            self._dashboard_mod = mod
        for m in _EMIT_RE.finditer(mod.src):
            etype = m.group(1)
            line = mod.src.count("\n", 0, m.start()) + 1
            self._emitted.setdefault(etype, []).append(mod.finding(
                "event-unregistered-emit", line,
                f"emits unregistered cluster event {etype!r}; declare "
                f"it in ray_tpu/observability/events.py"))
        return ()

    def finalize(self):
        if self._registry is None:
            return  # no schema in this tree — nothing to check against
        for etype, findings in sorted(self._emitted.items()):
            if etype not in self._registry:
                yield findings[0]
        rmod = self._registry_mod
        for etype, line in sorted(self._registry.items()):
            if etype not in self._emitted:
                yield rmod.finding(
                    "event-dead-type", line,
                    f"registered cluster event type {etype!r} has no "
                    f"emission site — dead schema entries mislead "
                    f"postmortems")
            if self._dashboard_doc is not None and \
                    etype not in self._dashboard_doc:
                yield rmod.finding(
                    "event-undocumented-type", line,
                    f"cluster event type {etype!r} is registered but "
                    f"missing from the GET /api/events row of the "
                    f"dashboard endpoint table "
                    f"({self._dashboard_mod.relpath} module docstring)")
