"""async-blocking: blocking calls inside ``async def`` bodies.

Every async def in this codebase runs on an :class:`EventLoopThread`
(rpc.py) — one wedged coroutine stalls heartbeats, lease dispatch and
every other handler sharing the loop. This is the bug class PR 5's
SIGUSR2 stack dumps keep diagnosing *post hoc*; here it fails review
instead.

Only the coroutine's *direct* scope is scanned: nested ``def``/
``lambda`` bodies are skipped because the idiomatic fix is exactly to
move the blocking call into a ``run_in_executor`` payload, and flagging
the payload would punish the fix. Awaited calls are never flagged.

Three rules:

- ``async-blocking-call``: a known-blocking API (``time.sleep``, sync
  ``subprocess``, sync socket ops, ``open``/file I/O, the sync
  ``RpcClient.call``) invoked without ``await``.
- ``async-unawaited-wait``: a bare ``x.wait()`` / ``x.result()`` /
  ``x.join()`` with no arguments and no await — either a blocking
  ``threading`` primitive on the loop or a forgotten ``await`` on an
  asyncio one; both wedge.
- ``async-blocking-transitive``: the same wedge one hop (or more)
  removed — a coroutine calling a *sync helper* that blocks somewhere
  down its call chain.  Summaries propagate "this sync function may
  block" up the package call graph to a fixpoint, so wrapping
  ``time.sleep`` in ``def _backoff():`` no longer hides it from
  review.  Handing the helper to an executor (``run_in_executor(None,
  helper)`` / ``asyncio.to_thread(helper)``) passes it un-called and
  is, as before, the sanctioned fix.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.lint._ast_util import (
    awaited_calls, call_name, consumed_calls, has_timeout, walk_scope,
)
from ray_tpu._private.lint.callgraph import get_call_graph
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

_BLOCKING_EXACT = {
    "time.sleep": "sleeps the whole event loop — use asyncio.sleep",
    "os.system": "blocks the loop for the child's lifetime",
    "os.popen": "blocks the loop on child I/O",
    "os.wait": "blocks the loop until a child exits",
    "socket.create_connection":
        "sync connect on the loop — use asyncio.open_connection",
    "subprocess.run": "blocks the loop for the child's lifetime",
    "subprocess.call": "blocks the loop for the child's lifetime",
    "subprocess.check_call": "blocks the loop for the child's lifetime",
    "subprocess.check_output": "blocks the loop for the child's lifetime",
    "subprocess.getoutput": "blocks the loop for the child's lifetime",
    "subprocess.getstatusoutput":
        "blocks the loop for the child's lifetime",
    "subprocess.Popen":
        "fork+exec can block the loop for tens of ms under load",
    "open": "sync file I/O on the event loop",
    "io.open": "sync file I/O on the event loop",
    "requests.get": "sync HTTP on the event loop",
    "requests.post": "sync HTTP on the event loop",
    "requests.request": "sync HTTP on the event loop",
    "urllib.request.urlopen": "sync HTTP on the event loop",
}

# Attribute-call suffixes that are blocking on their common receivers
# (sockets / pipes / the sync RpcClient.call transport).
_BLOCKING_SUFFIX = {
    ".recv": "sync socket/pipe read on the event loop",
    ".recv_into": "sync socket read on the event loop",
    ".accept": "sync accept on the event loop",
    ".sendall": "sync socket write on the event loop",
    ".call": ("sync RPC on the event loop — use 'await "
              "client.acall(...)'"),
}

# Bare x.wait()/x.join() with no bound: blocking threading primitive or
# forgotten await. ``.result`` is deliberately absent — ``fut.result()``
# on an already-completed asyncio future (the post-``asyncio.wait``
# idiom) is non-blocking and statically indistinguishable.
_WAITISH = (".wait", ".join")


def blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call blocks the event loop, or None if it doesn't
    (shared by the direct rule and the transitive summaries)."""
    name = call_name(call)
    if not name:
        return None
    why = _BLOCKING_EXACT.get(name)
    if name == "os.waitpid":
        flags = " ".join(ast.unparse(a) for a in call.args[1:])
        why = (None if "WNOHANG" in flags
               else "blocks the loop until the child exits — pass "
                    "os.WNOHANG or poll in an executor")
    if why is None and "." in name:
        for suffix, reason in _BLOCKING_SUFFIX.items():
            if name.endswith(suffix) and not name.endswith(".acall"):
                why = reason
                break
    return why


@register
class AsyncBlockingPass(LintPass):
    name = "async-blocking"
    rules = ("async-blocking-call", "async-unawaited-wait",
             "async-blocking-transitive")
    description = ("blocking calls and unawaited waits inside async "
                   "event-loop coroutines, including blocking buried "
                   "in sync helpers reached through the call graph")

    def __init__(self):
        self._mods: List[ModuleInfo] = []

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        self._mods.append(mod)
        out: List[Finding] = []
        awaited = awaited_calls(mod.tree)
        consumed = consumed_calls(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in walk_scope(node, skip_nested=True):
                if not isinstance(sub, ast.Call) or id(sub) in awaited:
                    continue
                name = call_name(sub)
                if not name:
                    continue
                why = blocking_reason(sub)
                if why is not None:
                    out.append(mod.finding(
                        "async-blocking-call", sub,
                        f"{name}() inside 'async def {node.name}': "
                        f"{why}"))
                    continue
                # x.wait() / x.join() with no bound, no await, and not
                # consumed by a wrapper call (asyncio.wait_for(ev.wait())
                # builds a coroutine — it doesn't block here).
                if "." in name and name.endswith(_WAITISH) \
                        and not sub.args and not has_timeout(sub) \
                        and id(sub) not in consumed:
                    out.append(mod.finding(
                        "async-unawaited-wait", sub,
                        f"unawaited, unbounded {name}() inside 'async "
                        f"def {node.name}': a threading primitive here "
                        f"blocks the loop forever; an asyncio one "
                        f"needs 'await'"))
        return out

    # ------------------------------------------- transitive detection

    def finalize(self) -> Iterable[Finding]:
        graph = get_call_graph(self._mods)
        # summary: id(sync func node) → (why, call chain to the block)
        summaries: Dict[int, Tuple[str, List[str]]] = {}
        for fi in graph.funcs:
            if fi.is_async:
                continue
            for sub in walk_scope(fi.node, skip_nested=True):
                if isinstance(sub, ast.Call):
                    why = blocking_reason(sub)
                    if why is not None:
                        summaries[id(fi.node)] = (
                            why, [fi.qualname, call_name(sub)])
                        break
        # Propagate "may block" up through sync callers to a fixpoint.
        changed = True
        while changed:
            changed = False
            for fi in graph.funcs:
                if fi.is_async or id(fi.node) in summaries:
                    continue
                for call, callee in graph.direct_calls(fi):
                    if callee is None or callee.is_async:
                        continue
                    hit = summaries.get(id(callee.node))
                    if hit is not None:
                        why, chain = hit
                        summaries[id(fi.node)] = (
                            why, [fi.qualname] + chain)
                        changed = True
                        break
        out: List[Finding] = []
        for fi in graph.funcs:
            if not fi.is_async:
                continue
            awaited = awaited_calls(fi.mod.tree)
            for call, callee in graph.direct_calls(fi):
                if callee is None or callee.is_async or \
                        id(call) in awaited:
                    continue
                hit = summaries.get(id(callee.node))
                if hit is None:
                    continue
                why, chain = hit
                out.append(fi.mod.finding(
                    "async-blocking-transitive", call,
                    f"{call_name(call)}() inside 'async def {fi.name}' "
                    f"blocks the event loop through its call chain "
                    f"{' -> '.join(chain)}: {why} — await an async "
                    f"variant or move the helper into an executor"))
        return out
