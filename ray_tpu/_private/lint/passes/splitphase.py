"""splitphase-dataflow: every start_* handle reaches its wait_* on
every path.

PR 12's ``collective-splitphase-unbalanced`` counted start/wait calls
per outermost function scope — good enough to catch a start with no
wait anywhere, structurally blind to *paths*: a handle dropped on an
early return, leaked through an ``except`` that swallows, stashed in a
container nobody drains, or waited twice.  An unwaited start is not a
leak but a hang: hop 0's DMA is in flight and hops 1..n-1 live in the
wait, so every peer blocks forever — the worst possible failure mode
at pod scale.  This pass replaces the heuristic with obligation
dataflow over the per-function CFG:

- ``splitphase-unwaited``: a path exists from a ``start_ring_*`` /
  ``start_quantized_ring_*`` call to function exit (including early
  returns and exception edges), an overwrite, or a ``del`` on which no
  matching ``wait_*`` consumed the handle.  Handles stashed in local
  containers stay tracked (``handles[i] = start(...)``,
  ``hs.append(start(...))``) and are discharged by waits over the
  container (``wait(handles[c])``, ``[wait(h) for h in hs]``).
- ``splitphase-double-wait``: a handle waited again after it was
  already waited on every path reaching the second wait — the second
  wait replays hops against a retired buffer.
- ``splitphase-mismatched-wait``: a ``wait_Y`` applied to a handle a
  ``start_X`` produced (allgather handle into a reduce-scatter wait).

One level of interprocedural summary keeps the idiomatic overlap
schedule clean: a local function that *returns* a start's handle is
itself a producer (``_start_rs``), one whose parameter flows into a
wait is a consumer (``_wait_rs``) — the zero.py chunked pipeline
typechecks without special cases.  Escapes out of view (returned to
the caller, passed to an unresolvable call, stored on an object)
discharge the obligation: the pass only flags what it can prove is
dropped.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name
from ray_tpu._private.lint.callgraph import get_call_graph
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import (
    ObligationEngine, Violation, cfgs_for_module, walk_no_scope,
)


def split_phase_key(name: str) -> Tuple[Optional[str], Optional[str]]:
    """("start"|"wait", op-key) for a split-phase ring call, else
    (None, None): ``start_ring_allgather`` and ``wait_ring_allgather``
    share the key ``ring_allgather``."""
    tail = name.rsplit(".", 1)[-1]
    for side in ("start", "wait"):
        prefix = side + "_"
        if tail.startswith(prefix):
            op = tail[len(prefix):]
            if op.startswith("ring_") or op.startswith("quantized_ring_"):
                return side, op
    return None, None


def _join_keys(keys: Set[str]) -> Optional[str]:
    return "|".join(sorted(keys)) if keys else None


class _Engine(ObligationEngine):
    report_double = True
    report_mismatch = True
    follow_exc = True

    def __init__(self, producers: Dict[str, Set[str]],
                 consumers: Dict[str, Set[str]]):
        # local-name → op keys, from the one-level callee summaries
        self._producers = producers
        self._consumers = consumers

    def creation_key(self, call: ast.Call) -> Optional[str]:
        name = call_name(call)
        side, op = split_phase_key(name)
        if side == "start":
            return op
        keys = self._producers.get(name.rsplit(".", 1)[-1])
        return _join_keys(keys) if keys else None

    def discharge_key(self, call: ast.Call) -> Optional[str]:
        name = call_name(call)
        side, op = split_phase_key(name)
        if side == "wait":
            return op
        keys = self._consumers.get(name.rsplit(".", 1)[-1])
        return _join_keys(keys) if keys else None

    def keys_match(self, creation: str, discharge: str) -> bool:
        return bool(set(creation.split("|")) & set(discharge.split("|")))


@register
class SplitPhasePass(LintPass):
    name = "splitphase-dataflow"
    rules = ("splitphase-unwaited", "splitphase-double-wait",
             "splitphase-mismatched-wait")
    description = ("dataflow tracking of split-phase collective handles: "
                   "every start_* must reach exactly one matching wait_* "
                   "on every path (early returns, exception edges, and "
                   "container stashes included)")

    def __init__(self):
        self._mods: List[ModuleInfo] = []

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        graph = get_call_graph(self._mods)
        out: List[Finding] = []
        for mod in self._mods:
            out.extend(self._check(mod, graph))
        return out

    # ------------------------------------------------------- summaries

    def _summaries(self, mod: ModuleInfo
                   ) -> Tuple[Dict[str, Set[str]], Dict[str, Set[str]]]:
        """Local one-level summaries: function name → op keys it
        produces (returns a fresh start handle) / consumes (a param
        flows into a wait)."""
        producers: Dict[str, Set[str]] = {}
        consumers: Dict[str, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in (node.args.posonlyargs
                                      + node.args.args
                                      + node.args.kwonlyargs)}
            # Names assigned from a start call inside this function.
            started_names: Dict[str, str] = {}
            for sub in walk_no_scope(node):
                if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call):
                    side, op = split_phase_key(call_name(sub.value))
                    if side == "start":
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                started_names[t.id] = op
            for sub in walk_no_scope(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    for c in walk_no_scope(sub.value):
                        if isinstance(c, ast.Call):
                            side, op = split_phase_key(call_name(c))
                            if side == "start":
                                producers.setdefault(node.name,
                                                     set()).add(op)
                    if isinstance(sub.value, ast.Name) and \
                            sub.value.id in started_names:
                        producers.setdefault(node.name, set()).add(
                            started_names[sub.value.id])
                elif isinstance(sub, ast.Call):
                    side, op = split_phase_key(call_name(sub))
                    if side == "wait":
                        for arg in sub.args:
                            if isinstance(arg, ast.Name) and \
                                    arg.id in params:
                                consumers.setdefault(node.name,
                                                     set()).add(op)
        return producers, consumers

    # ----------------------------------------------------------- check

    def _check(self, mod: ModuleInfo, graph) -> Iterable[Finding]:
        producers, consumers = self._summaries(mod)
        if not producers and not self._has_split_phase(mod):
            return
        engine = _Engine(producers, consumers)
        for fn, cfg in cfgs_for_module(mod).items():
            for v in engine.analyze(cfg):
                yield self._finding(mod, fn, v)

    @staticmethod
    def _has_split_phase(mod: ModuleInfo) -> bool:
        return "start_ring_" in mod.src or "start_quantized_ring_" \
            in mod.src or "wait_ring_" in mod.src \
            or "wait_quantized_ring_" in mod.src

    def _finding(self, mod: ModuleInfo, fn, v: Violation) -> Finding:
        op = call_name(v.origin).rsplit(".", 1)[-1] \
            if isinstance(v.origin, ast.Call) else "start"
        where = f"in {fn.name}()"
        if v.kind == "double":
            return mod.finding(
                "splitphase-double-wait", v.node,
                f"{op} handle {where} is waited again on a path where "
                f"it was already waited: the second wait replays ring "
                f"hops against a retired buffer — thread each handle "
                f"to exactly one wait")
        if v.kind == "mismatch":
            return mod.finding(
                "splitphase-mismatched-wait", v.node,
                f"handle from {op} {where} flows into a wait for a "
                f"different op ({v.detail}): the wait replays the "
                f"wrong hop schedule and the ring deadlocks or "
                f"corrupts — match start_X with wait_X")
        how = {
            "dropped": "is discarded where it stands",
            "overwritten": "is overwritten while still unwaited",
            "deleted": "is deleted while still unwaited",
            "exit": "misses its wait on some path to function exit "
                    "(early return, exception edge, or a container "
                    "nothing drains)",
        }[v.kind]
        return mod.finding(
            "splitphase-unwaited", v.node,
            f"{op} handle {where} {how}: hops 1..n-1 of the ring live "
            f"in the wait, so every peer blocks in its own wait and "
            f"the mesh hangs — thread the handle to a matching wait_* "
            f"on every path")
