"""sharding-axis-consistency: axis names used under a shard_map/pmap
must exist on the mesh that wraps them.

``collective-unknown-axis`` checks a *module-wide* axis vocabulary —
good enough to catch outright typos, blind to context: a module that
declares meshes ``("data", "model")`` and ``("stage",)`` will happily
accept a ``psum(x, "model")`` inside a function shard_mapped over the
``("stage",)`` mesh.  That program is well-formed to every unit test
(CPU backends trace with a 1-device mesh that never resolves axes) and
dies at trace time on the pod, inside a 30-minute compile.

This pass checks the *binding* instead: for every ``shard_map`` /
``pmap`` wrap whose mesh resolves to a literal axis declaration in the
same module, the wrapped function's collectives and the wrap's own
PartitionSpecs must only name axes that mesh has.

- ``sharding-axis-undeclared``: a collective inside the wrapped
  function (resolved by name, or an inline lambda) names an axis the
  enclosing mesh does not declare.
- ``sharding-spec-axis-undeclared``: a ``P(...)``/``PartitionSpec``
  entry in the wrap's ``in_specs``/``out_specs`` — or in a
  ``NamedSharding(mesh, ...)`` over a resolvable mesh — names an axis
  the mesh does not declare (the spec silently falls back to
  replication or fails at trace time, depending on version: both are
  wrong).

Unresolvable meshes (parameters, attributes, anything not assigned a
literal ``Mesh``/``make_mesh`` in this module) skip the check entirely:
precision over recall.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set

from ray_tpu._private.lint._ast_util import call_name, kwarg
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import walk_no_scope
from ray_tpu._private.lint.passes.collectives import (
    _axis_strings, _collective_axis,
)

_MESH_CTORS = {"Mesh", "make_mesh", "device_mesh"}
_SPEC_CTORS = {"P", "PartitionSpec"}


def _mesh_axes_from_ctor(call: ast.Call) -> Optional[FrozenSet[str]]:
    """Axis names a mesh constructor declares, when literal."""
    tail = call_name(call).rsplit(".", 1)[-1]
    if tail not in _MESH_CTORS:
        return None
    axes: Set[str] = set()
    cands: List[ast.expr] = []
    if len(call.args) > 1:
        cands.append(call.args[1])
    for kw in call.keywords:
        if kw.arg in ("axis_names", "axes", "mesh_shape"):
            cands.append(kw.value)
    for c in cands:
        if isinstance(c, ast.Dict):
            for k in c.keys:
                if isinstance(k, ast.Constant) and isinstance(
                        k.value, str):
                    axes.add(k.value)
        else:
            axes.update(_axis_strings(c))
    return frozenset(axes) if axes else None


def _spec_axes(expr: ast.expr) -> Iterable[ast.Constant]:
    """String constants inside P(...)/PartitionSpec(...) calls under
    ``expr`` (nested tuples included: P(("dp", "fsdp"), None))."""
    for n in walk_no_scope(expr):
        if isinstance(n, ast.Call) and \
                call_name(n).rsplit(".", 1)[-1] in _SPEC_CTORS:
            for sub in ast.walk(n):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    yield sub


@register
class ShardingAxisPass(LintPass):
    name = "sharding-axis-consistency"
    rules = ("sharding-axis-undeclared", "sharding-spec-axis-undeclared")
    description = ("collectives and PartitionSpecs under a "
                   "shard_map/pmap may only name axes the wrapping "
                   "mesh declares — a context mismatch passes every "
                   "CPU test and fails at trace time on the pod")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if "shard_map" not in mod.src and "pmap" not in mod.src and \
                "NamedSharding" not in mod.src:
            return ()
        meshes = self._mesh_bindings(mod)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = call_name(node).rsplit(".", 1)[-1]
            if tail == "shard_map":
                out.extend(self._check_shard_map(mod, node, meshes))
            elif tail == "pmap":
                out.extend(self._check_pmap(mod, node))
            elif tail == "NamedSharding":
                out.extend(self._check_named_sharding(mod, node, meshes))
        return out

    # -------------------------------------------------- mesh resolution

    @staticmethod
    def _mesh_bindings(mod: ModuleInfo) -> Dict[str, FrozenSet[str]]:
        """name → declared axes, for every name assigned a literal mesh
        constructor anywhere in the module.  Reassignments union (a name
        holding either mesh may use either vocabulary — no FPs)."""
        out: Dict[str, FrozenSet[str]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                axes = _mesh_axes_from_ctor(node.value)
                if axes is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = out.get(t.id, frozenset()) | axes
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and \
                            isinstance(item.optional_vars, ast.Name):
                        axes = _mesh_axes_from_ctor(item.context_expr)
                        if axes is not None:
                            name = item.optional_vars.id
                            out[name] = out.get(name, frozenset()) | axes
        return out

    def _resolve_mesh(self, expr: Optional[ast.expr],
                      meshes: Dict[str, FrozenSet[str]]
                      ) -> Optional[FrozenSet[str]]:
        if expr is None:
            return None
        if isinstance(expr, ast.Call):
            return _mesh_axes_from_ctor(expr)
        if isinstance(expr, ast.Name):
            return meshes.get(expr.id)
        return None

    # ------------------------------------------------------------ checks

    def _check_shard_map(self, mod: ModuleInfo, call: ast.Call,
                         meshes: Dict[str, FrozenSet[str]]
                         ) -> Iterable[Finding]:
        mesh_expr = kwarg(call, "mesh")
        if mesh_expr is None and len(call.args) > 1:
            mesh_expr = call.args[1]
        axes = self._resolve_mesh(mesh_expr, meshes)
        if axes is None:
            return
        # Specs named on the wrap itself.
        spec_exprs = [kw.value for kw in call.keywords
                      if kw.arg in ("in_specs", "out_specs")]
        spec_exprs += call.args[2:4]
        for se in spec_exprs:
            for const in _spec_axes(se):
                if const.value not in axes:
                    yield mod.finding(
                        "sharding-spec-axis-undeclared", const,
                        f"P({const.value!r}) in a shard_map spec, but "
                        f"the mesh only declares {sorted(axes)}: the "
                        f"spec axis resolves to nothing and the "
                        f"dimension is silently replicated (or trace "
                        f"fails, version-dependent) — use a declared "
                        f"axis")
        fn_node = self._wrapped_fn(call, mod)
        if fn_node is None:
            return
        yield from self._check_body_axes(mod, fn_node, axes, "shard_map")

    def _check_pmap(self, mod: ModuleInfo,
                    call: ast.Call) -> Iterable[Finding]:
        axis_expr = kwarg(call, "axis_name")
        names = _axis_strings(axis_expr) if axis_expr is not None else []
        if not names:
            return
        fn_node = self._wrapped_fn(call, mod)
        if fn_node is None:
            return
        yield from self._check_body_axes(mod, fn_node, frozenset(names),
                                         "pmap")

    def _check_named_sharding(self, mod: ModuleInfo, call: ast.Call,
                              meshes: Dict[str, FrozenSet[str]]
                              ) -> Iterable[Finding]:
        mesh_expr = call.args[0] if call.args else kwarg(call, "mesh")
        axes = self._resolve_mesh(mesh_expr, meshes)
        if axes is None:
            return
        for arg in call.args[1:] + [kw.value for kw in call.keywords
                                    if kw.arg == "spec"]:
            for const in _spec_axes(arg):
                if const.value not in axes:
                    yield mod.finding(
                        "sharding-spec-axis-undeclared", const,
                        f"NamedSharding over a mesh declaring "
                        f"{sorted(axes)} uses P({const.value!r}): the "
                        f"axis does not exist on that mesh — the array "
                        f"lands replicated where you meant sharded")

    def _check_body_axes(self, mod: ModuleInfo, fn_node: ast.AST,
                         axes: FrozenSet[str],
                         wrap: str) -> Iterable[Finding]:
        for sub in ast.walk(fn_node):
            if not isinstance(sub, ast.Call):
                continue
            op, used = _collective_axis(sub)
            if op is None:
                continue
            for axis in used:
                if axis not in axes:
                    yield mod.finding(
                        "sharding-axis-undeclared", sub,
                        f"{op}(..., {axis!r}) inside a function "
                        f"wrapped by {wrap} over mesh axes "
                        f"{sorted(axes)}: the axis is not bound in "
                        f"this context, so tracing fails on the pod "
                        f"(CPU tests never resolve it) — psum over an "
                        f"axis the mesh declares")

    @staticmethod
    def _wrapped_fn(call: ast.Call, mod: ModuleInfo) -> Optional[ast.AST]:
        """The function a shard_map/pmap wraps, when resolvable: an
        inline lambda, or a unique same-module def by name."""
        if not call.args:
            return None
        target = call.args[0]
        if isinstance(target, ast.Lambda):
            return target
        if not isinstance(target, ast.Name):
            return None
        cands = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and n.name == target.id]
        return cands[0] if len(cands) == 1 else None
