"""collective-consistency: axis-name and branch discipline for
collectives.

Two SPMD invariants no unit test on a 1-device CPU backend can check:

- ``collective-unknown-axis``: a ``psum``/``all_gather``/``ppermute``
  axis name must be bound by some enclosing mesh / axis declaration.
  A typo'd axis fails only when the program finally runs on a real
  mesh — at pod bring-up, inside a 30-minute compile. The pass
  compares every literal axis argument against the axes declared
  anywhere in the same module (``Mesh(...)`` tuples, ``make_mesh``
  dict keys, ``PartitionSpec``/``P`` entries, ``axis_name=``-style
  defaults and kwargs) plus the repo-wide ``AXIS_ORDER`` axes.
- ``collective-divergent-branches``: inside a function that issues
  collectives, an ``if``/``else`` whose two branches issue *different*
  collective sequences hangs the mesh when replicas disagree on the
  predicate — each replica enters a different collective schedule and
  everyone waits forever (the Podracer actor/learner split is the most
  sensitive consumer). Branches where only one side has collectives
  are the common static fallback shape (``if axis_size == 1``) and are
  not flagged.

Three more from the Pallas collective backend (these invariants are
checked at runtime too, but only on a live group — the lint catches
them at review time):

- ``collective-member-mismatch``: ``create_collective_group`` /
  ``init_collective_group`` with literal world_size/ranks that cannot
  form a group (rank out of ``[0, world_size)``, rank-list length or
  duplicates disagreeing with world_size). A mismatched membership
  declaration hangs rendezvous until the timeout.
- ``collective-dtype-drift``: an ``if``/``else`` whose branches issue
  the SAME collective sequence but cast the payload to *different*
  explicit dtypes (``.astype(bf16)`` vs ``.astype(f32)``) — ranks
  disagreeing on the predicate put different wire formats on the ring
  and the reduction is garbage (or deadlocks on size mismatch).
- ``collective-quantized-nonfloat``: a quantized allreduce whose
  payload is visibly integer (``.astype(int32)`` / ``dtype=int8``).
  Quantizing integer gradients silently corrupts them; the runtime
  raises TypeError, the lint says so before the job is launched.

One from the quantized overlap machinery:

- ``collective-ef-nonfloat``: an error-feedback buffer assigned an
  explicitly integer dtype.  EF accumulates the quantizer's *residual*
  (sub-quantum values by construction); an int EF rounds every residual
  to zero and silently degenerates to plain quantization.

Split-phase start/wait balance used to live here as a per-scope count
(``collective-splitphase-unbalanced``); it is now the path-sensitive
``splitphase-dataflow`` pass, which sees early returns, exception
edges, and container stashes the count never could.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name, walk_scope
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

# Repo-wide mesh axes (ray_tpu/parallel/mesh.py AXIS_ORDER): usable from
# any module without a local declaration.
_GLOBAL_AXES = {"data", "fsdp", "pipe", "seq", "tensor"}

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "pbroadcast", "axis_index",
    "axis_size", "pcast", "pvary",
}


_INT_DTYPES = {
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool", "bool_",
}

# Calls that quantize their payload before the ring reduction.
_QUANTIZED_CALLS = {"quantized_ring_allreduce",
                    "start_quantized_ring_reduce_scatter"}

# Error-feedback buffer names (collective-ef-nonfloat targets).
_EF_EXACT = {"ef", "error_feedback"}


def _is_ef_name(name: str) -> bool:
    low = name.lower()
    return (low in _EF_EXACT or "error_feedback" in low
            or low.endswith("_ef") or low.startswith("ef_"))


def _assigned_dtype(value: ast.expr) -> Optional[str]:
    """Explicit dtype of an assignment's RHS, when visible: the
    ``astype``/``dtype=`` forms of `_payload_dtype` plus the positional
    dtype of the array constructors (``jnp.zeros(shape, jnp.int8)``)."""
    dtype = _payload_dtype(value)
    if dtype is not None:
        return dtype
    if isinstance(value, ast.Call):
        ctor = call_name(value).rsplit(".", 1)[-1]
        if ctor in ("zeros", "ones", "empty", "zeros_like", "ones_like",
                    "empty_like") and len(value.args) > 1:
            return _dtype_name(value.args[1])
        if ctor == "full" and len(value.args) > 2:
            return _dtype_name(value.args[2])
    return None


def _dtype_name(node: Optional[ast.expr]) -> Optional[str]:
    """Literal dtype spelled by an expression: ``jnp.bfloat16`` →
    "bfloat16", ``"int32"`` → "int32", ``np.int8`` → "int8"."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _payload_dtype(node: Optional[ast.expr]) -> Optional[str]:
    """Explicit dtype of a collective's payload expression, when visible:
    ``x.astype(jnp.bfloat16)``, ``jnp.zeros(..., dtype=jnp.int32)``."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
        if node.args:
            return _dtype_name(node.args[0])
    for kw in node.keywords:
        if kw.arg == "dtype":
            return _dtype_name(kw.value)
    return None


def _axis_strings(node: Optional[ast.expr]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _collective_axis(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    """(op, literal axis names) for a collective call, else (None, [])."""
    name = call_name(call)
    op = name.rsplit(".", 1)[-1]
    if op not in _COLLECTIVES:
        return None, []
    axes: List[str] = []
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis", "axis_names"):
            axes.extend(_axis_strings(kw.value))
    if not axes:
        # Positional axis arg: arg 0 for axis_index/axis_size, arg 1
        # for value-first collectives.
        idx = 0 if op in ("axis_index", "axis_size") else 1
        if len(call.args) > idx:
            axes.extend(_axis_strings(call.args[idx]))
    return op, axes


def _declared_axes(mod: ModuleInfo) -> Set[str]:
    axes: Set[str] = set(_GLOBAL_AXES)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = call_name(node).rsplit(".", 1)[-1]
            if name == "Mesh":
                if len(node.args) > 1:
                    axes.update(_axis_strings(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes.update(_axis_strings(kw.value))
            elif name in ("PartitionSpec", "P", "NamedSharding"):
                for a in node.args:
                    axes.update(_axis_strings(a))
            elif name in ("make_mesh", "device_mesh"):
                cands = list(node.args)
                cands += [kw.value for kw in node.keywords
                          if kw.arg in ("axes", "mesh_shape")]
                for c in cands:
                    if isinstance(c, ast.Dict):
                        for k in c.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                axes.add(k.value)
            # Any axis-ish kwarg on a NON-collective call binds the name
            # for this module's collectives (e.g. shard_map wrappers
            # taking axis_name="sp", functools.partial(..., axis_name=..)).
            # Collective calls are excluded: a psum's own axis_name must
            # not vouch for itself, or kwarg-form typos become invisible.
            if name not in _COLLECTIVES:
                for kw in node.keywords:
                    if kw.arg and ("axis" in kw.arg) and \
                            kw.arg not in ("axis_index_groups",):
                        axes.update(_axis_strings(kw.value))
        elif isinstance(node, ast.arguments):
            # String defaults of axis-named parameters.
            pos = node.posonlyargs + node.args + node.kwonlyargs
            defaults = list(node.defaults) + list(node.kw_defaults)
            first_default = len(pos) - len(defaults)
            for i, a in enumerate(pos):
                if i < first_default:
                    continue
                if "axis" in a.arg:
                    axes.update(_axis_strings(defaults[i - first_default]))
    return axes


@register
class CollectivesPass(LintPass):
    name = "collective-consistency"
    rules = ("collective-unknown-axis", "collective-divergent-branches",
             "collective-member-mismatch", "collective-dtype-drift",
             "collective-quantized-nonfloat", "collective-ef-nonfloat")
    description = ("collective axis names must be declared; conditional "
                   "branches must issue identical collective sequences "
                   "with consistent wire dtypes; group membership "
                   "declarations must be coherent; quantized allreduce "
                   "takes float payloads only; error-feedback buffers "
                   "must be float")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        declared = _declared_axes(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                op, axes = _collective_axis(node)
                if op is not None:
                    for axis in axes:
                        if axis not in declared:
                            out.append(mod.finding(
                                "collective-unknown-axis", node,
                                f"{op}(..., {axis!r}): axis {axis!r} is "
                                f"not declared by any mesh/PartitionSpec/"
                                f"axis_name binding in this module (known "
                                f"here: {sorted(declared)}) — a typo'd "
                                f"axis only fails at pod bring-up"))
                out.extend(self._check_membership(mod, node))
                out.extend(self._check_quantized(mod, node))
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                out.extend(self._check_ef_dtype(mod, node))
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_branches(mod, node))
        return out

    def _check_membership(self, mod: ModuleInfo,
                          call: ast.Call) -> Iterable[Finding]:
        name = call_name(call).rsplit(".", 1)[-1]

        def _int(node) -> Optional[int]:
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             int):
                return node.value
            return None

        def _arg(pos: int, kw_name: str):
            for kw in call.keywords:
                if kw.arg == kw_name:
                    return kw.value
            return call.args[pos] if len(call.args) > pos else None

        if name == "init_collective_group":
            world = _int(_arg(0, "world_size"))
            rank = _int(_arg(1, "rank"))
            if world is not None and rank is not None and \
                    not (0 <= rank < world):
                yield mod.finding(
                    "collective-member-mismatch", call,
                    f"init_collective_group(world_size={world}, "
                    f"rank={rank}): rank outside [0, {world}) — this "
                    f"member can never join and rendezvous hangs until "
                    f"the timeout")
        elif name == "create_collective_group":
            world = _int(_arg(1, "world_size"))
            ranks_node = _arg(2, "ranks")
            if world is None or not isinstance(ranks_node,
                                               (ast.List, ast.Tuple)):
                return
            ranks = [_int(e) for e in ranks_node.elts]
            if any(r is None for r in ranks):
                return
            if len(ranks) != world or sorted(ranks) != list(range(world)):
                yield mod.finding(
                    "collective-member-mismatch", call,
                    f"create_collective_group(world_size={world}, "
                    f"ranks={ranks}): ranks must be exactly "
                    f"0..{world - 1} once each — a mismatched "
                    f"membership declaration leaves the group waiting "
                    f"for members that never come")

    def _check_quantized(self, mod: ModuleInfo,
                         call: ast.Call) -> Iterable[Finding]:
        name = call_name(call).rsplit(".", 1)[-1]
        quantized = name in _QUANTIZED_CALLS
        if not quantized and name in ("allreduce", "device_allreduce"):
            quantized = any(
                kw.arg == "quantized" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in call.keywords)
        if not quantized or not call.args:
            return
        dtype = _payload_dtype(call.args[0])
        if dtype in _INT_DTYPES:
            yield mod.finding(
                "collective-quantized-nonfloat", call,
                f"{name}(<{dtype} payload>): int8 quantization of "
                f"integer data silently corrupts it (scale/round is "
                f"only meaningful for floats) — the runtime raises "
                f"TypeError; reduce with op='sum' unquantized instead")

    def _check_ef_dtype(self, mod: ModuleInfo, node) -> Iterable[Finding]:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not any(_is_ef_name(n) for n in names) or node.value is None:
            return
        dtype = _assigned_dtype(node.value)
        if dtype in _INT_DTYPES:
            name = next(n for n in names if _is_ef_name(n))
            yield mod.finding(
                "collective-ef-nonfloat", node,
                f"error-feedback buffer {name!r} assigned dtype "
                f"{dtype!r}: EF accumulates the quantizer's sub-quantum "
                f"residual, which an integer buffer rounds to zero — "
                f"keep EF in float32")

    def _branch_sig(self, stmts):
        """Per-branch collective signature: [(op, axes, payload_dtype)].
        op/axes feed the divergence check; dtype feeds the drift check."""
        sig = []
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(sub, ast.Call):
                    op, axes = _collective_axis(sub)
                    if op is not None and op not in ("axis_index",
                                                     "axis_size"):
                        dtype = (_payload_dtype(sub.args[0])
                                 if sub.args else None)
                        sig.append((op, tuple(sorted(axes)), dtype))
        return sig

    def _check_branches(self, mod: ModuleInfo, fn) -> Iterable[Finding]:
        for node in walk_scope(fn, skip_nested=True):
            if not isinstance(node, ast.If) or not node.orelse:
                continue
            body_sig = self._branch_sig(node.body)
            else_sig = self._branch_sig(node.orelse)
            # One-sided collectives are the static-fallback shape
            # ("if n == 1: no ring"); only flag when BOTH branches
            # issue collectives and disagree.
            if not body_sig or not else_sig:
                continue
            body_ops = [(op, axes) for op, axes, _ in body_sig]
            else_ops = [(op, axes) for op, axes, _ in else_sig]
            if body_ops != else_ops:
                yield mod.finding(
                    "collective-divergent-branches", node,
                    f"'if' branches inside {fn.name}() issue different "
                    f"collective sequences ({body_ops} vs {else_ops}): "
                    f"replicas disagreeing on the predicate enter "
                    f"different collective schedules and the mesh "
                    f"hangs — hoist the collectives out of the branch "
                    f"or make both arms issue the same sequence")
                continue
            # Same schedule: do the two arms put the same wire format on
            # it? Only flag EXPLICIT disagreements (both arms cast).
            for (op, axes, bd), (_, _, ed) in zip(body_sig, else_sig):
                if bd is not None and ed is not None and bd != ed:
                    yield mod.finding(
                        "collective-dtype-drift", node,
                        f"'if' branches inside {fn.name}() issue the "
                        f"same {op} over {list(axes)} but cast the "
                        f"payload to {bd!r} vs {ed!r}: ranks that "
                        f"disagree on the predicate reduce mixed wire "
                        f"formats — pick one dtype before the branch")
                    break
