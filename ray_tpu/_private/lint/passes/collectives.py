"""collective-consistency: axis-name and branch discipline for
collectives.

Two SPMD invariants no unit test on a 1-device CPU backend can check:

- ``collective-unknown-axis``: a ``psum``/``all_gather``/``ppermute``
  axis name must be bound by some enclosing mesh / axis declaration.
  A typo'd axis fails only when the program finally runs on a real
  mesh — at pod bring-up, inside a 30-minute compile. The pass
  compares every literal axis argument against the axes declared
  anywhere in the same module (``Mesh(...)`` tuples, ``make_mesh``
  dict keys, ``PartitionSpec``/``P`` entries, ``axis_name=``-style
  defaults and kwargs) plus the repo-wide ``AXIS_ORDER`` axes.
- ``collective-divergent-branches``: inside a function that issues
  collectives, an ``if``/``else`` whose two branches issue *different*
  collective sequences hangs the mesh when replicas disagree on the
  predicate — each replica enters a different collective schedule and
  everyone waits forever (the Podracer actor/learner split is the most
  sensitive consumer). Branches where only one side has collectives
  are the common static fallback shape (``if axis_size == 1``) and are
  not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint._ast_util import call_name, walk_scope
from ray_tpu._private.lint.core import Finding, LintPass, ModuleInfo, register

# Repo-wide mesh axes (ray_tpu/parallel/mesh.py AXIS_ORDER): usable from
# any module without a local declaration.
_GLOBAL_AXES = {"data", "fsdp", "pipe", "seq", "tensor"}

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "pbroadcast", "axis_index",
    "axis_size", "pcast", "pvary",
}


def _axis_strings(node: Optional[ast.expr]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _collective_axis(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    """(op, literal axis names) for a collective call, else (None, [])."""
    name = call_name(call)
    op = name.rsplit(".", 1)[-1]
    if op not in _COLLECTIVES:
        return None, []
    axes: List[str] = []
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis", "axis_names"):
            axes.extend(_axis_strings(kw.value))
    if not axes:
        # Positional axis arg: arg 0 for axis_index/axis_size, arg 1
        # for value-first collectives.
        idx = 0 if op in ("axis_index", "axis_size") else 1
        if len(call.args) > idx:
            axes.extend(_axis_strings(call.args[idx]))
    return op, axes


def _declared_axes(mod: ModuleInfo) -> Set[str]:
    axes: Set[str] = set(_GLOBAL_AXES)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = call_name(node).rsplit(".", 1)[-1]
            if name == "Mesh":
                if len(node.args) > 1:
                    axes.update(_axis_strings(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes.update(_axis_strings(kw.value))
            elif name in ("PartitionSpec", "P", "NamedSharding"):
                for a in node.args:
                    axes.update(_axis_strings(a))
            elif name in ("make_mesh", "device_mesh"):
                cands = list(node.args)
                cands += [kw.value for kw in node.keywords
                          if kw.arg in ("axes", "mesh_shape")]
                for c in cands:
                    if isinstance(c, ast.Dict):
                        for k in c.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                axes.add(k.value)
            # Any axis-ish kwarg on a NON-collective call binds the name
            # for this module's collectives (e.g. shard_map wrappers
            # taking axis_name="sp", functools.partial(..., axis_name=..)).
            # Collective calls are excluded: a psum's own axis_name must
            # not vouch for itself, or kwarg-form typos become invisible.
            if name not in _COLLECTIVES:
                for kw in node.keywords:
                    if kw.arg and ("axis" in kw.arg) and \
                            kw.arg not in ("axis_index_groups",):
                        axes.update(_axis_strings(kw.value))
        elif isinstance(node, ast.arguments):
            # String defaults of axis-named parameters.
            pos = node.posonlyargs + node.args + node.kwonlyargs
            defaults = list(node.defaults) + list(node.kw_defaults)
            first_default = len(pos) - len(defaults)
            for i, a in enumerate(pos):
                if i < first_default:
                    continue
                if "axis" in a.arg:
                    axes.update(_axis_strings(defaults[i - first_default]))
    return axes


@register
class CollectivesPass(LintPass):
    name = "collective-consistency"
    rules = ("collective-unknown-axis", "collective-divergent-branches")
    description = ("collective axis names must be declared; conditional "
                   "branches must issue identical collective sequences")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        declared = _declared_axes(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                op, axes = _collective_axis(node)
                if op is None:
                    continue
                for axis in axes:
                    if axis not in declared:
                        out.append(mod.finding(
                            "collective-unknown-axis", node,
                            f"{op}(..., {axis!r}): axis {axis!r} is not "
                            f"declared by any mesh/PartitionSpec/"
                            f"axis_name binding in this module (known "
                            f"here: {sorted(declared)}) — a typo'd "
                            f"axis only fails at pod bring-up"))
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_branches(mod, node))
        return out

    def _branch_sig(self, stmts) -> List[Tuple[str, Tuple[str, ...]]]:
        sig = []
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(sub, ast.Call):
                    op, axes = _collective_axis(sub)
                    if op is not None and op not in ("axis_index",
                                                     "axis_size"):
                        sig.append((op, tuple(sorted(axes))))
        return sig

    def _check_branches(self, mod: ModuleInfo, fn) -> Iterable[Finding]:
        for node in walk_scope(fn, skip_nested=True):
            if not isinstance(node, ast.If) or not node.orelse:
                continue
            body_sig = self._branch_sig(node.body)
            else_sig = self._branch_sig(node.orelse)
            # One-sided collectives are the static-fallback shape
            # ("if n == 1: no ring"); only flag when BOTH branches
            # issue collectives and disagree.
            if body_sig and else_sig and body_sig != else_sig:
                yield mod.finding(
                    "collective-divergent-branches", node,
                    f"'if' branches inside {fn.name}() issue different "
                    f"collective sequences ({body_sig} vs {else_sig}): "
                    f"replicas disagreeing on the predicate enter "
                    f"different collective schedules and the mesh "
                    f"hangs — hoist the collectives out of the branch "
                    f"or make both arms issue the same sequence")
