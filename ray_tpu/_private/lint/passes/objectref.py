"""objectref-leak: dropped or leaked ObjectRefs pin plasma forever.

An ``ObjectRef`` is a distributed refcount: as long as the driver-side
handle is reachable the owner pins the value in its object store.  Two
ways to get that wrong, one per rule:

- ``objectref-dropped``: a ``.remote()`` / ``put()`` result discarded
  where it stands (bare expression statement).  Fire-and-forget hides
  the task's exceptions *and* — because the ref is dropped before the
  task finishes — races lineage cleanup; the PR-3 lease-orphan fix
  chased exactly this shape at runtime.
- ``objectref-leak``: a ref bound to a local that is then overwritten,
  deleted, or falls out of scope on some path with no ``get`` /
  ``wait`` / ``await`` and no escape (returned, yielded, stored into a
  structure, passed to another call).  The binding dies, the
  distributed refcount does not drop until GC gets around to it, and
  under churn the object store fills with orphans.

Tracking is deliberately generous about discharge: *any* read of the
ref counts (``loads_consume`` — passing it to ``get``, sticking it in
a list, formatting it into a log line all keep it visible), so a
finding means the ref provably went nowhere.  Exception edges are not
followed (a raise unwinding past a ref is GC's job, not a bug), which
keeps ``try: ref = f.remote(); ...`` patterns quiet.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ray_tpu._private.lint._ast_util import call_name
from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register,
)
from ray_tpu._private.lint.dataflow import (
    ObligationEngine, Violation, cfgs_for_module,
)

_PUT_NAMES = {"ray.put", "ray_tpu.put"}


def _ref_creation(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if name in ("ray.remote", "ray_tpu.remote"):
        return None   # the decorator builds a RemoteFunction, not a ref
    if name.endswith(".remote"):
        return "ref"
    if name in _PUT_NAMES or name.endswith(".put") and \
            name.rsplit(".", 2)[-2] in ("ray", "ray_tpu"):
        return "ref"
    return None


class _Engine(ObligationEngine):
    loads_consume = True
    follow_exc = False
    report_double = False
    report_mismatch = False

    def creation_key(self, call: ast.Call) -> Optional[str]:
        return _ref_creation(call)

    def discharge_key(self, call: ast.Call) -> Optional[str]:
        return None


@register
class ObjectRefLeakPass(LintPass):
    name = "objectref-leak"
    rules = ("objectref-dropped", "objectref-leak")
    description = ("ObjectRefs must be kept and resolved: a dropped or "
                   "overwritten .remote()/put() result pins the object "
                   "store and hides task failures")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if ".remote" not in mod.src and ".put" not in mod.src:
            return ()
        engine = _Engine()
        out: List[Finding] = []
        for fn, cfg in cfgs_for_module(mod).items():
            for v in engine.analyze(cfg):
                out.append(self._finding(mod, fn, v))
        return out

    def _finding(self, mod: ModuleInfo, fn, v: Violation) -> Finding:
        call = call_name(v.origin) if isinstance(v.origin, ast.Call) \
            else "remote call"
        if v.kind == "dropped":
            return mod.finding(
                "objectref-dropped", v.node,
                f"result of {call}(...) in {fn.name}() is discarded: "
                f"fire-and-forget hides the task's exceptions and "
                f"races lineage cleanup — keep the ref and get() it, "
                f"or suppress with a justification if detaching is "
                f"intentional")
        how = {
            "overwritten": "is overwritten",
            "deleted": "is deleted",
            "exit": "goes out of scope on some path",
        }.get(v.kind, "is lost")
        return mod.finding(
            "objectref-leak", v.node,
            f"ObjectRef from {call}(...) in {fn.name}() {how} without "
            f"get/wait/await or escaping to a caller: the distributed "
            f"refcount outlives the binding and pins plasma until GC — "
            f"resolve or return every ref on every path")
