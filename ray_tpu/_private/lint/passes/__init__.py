"""Built-in graftlint passes. Importing this package registers them."""

from ray_tpu._private.lint.passes import (  # noqa: F401
    async_blocking,
    collectives,
    control_loop,
    deadlock,
    events,
    jit_hygiene,
    locks,
    metrics,
)
