"""Built-in graftlint passes. Importing this package registers them."""

from ray_tpu._private.lint.passes import (  # noqa: F401
    async_blocking,
    atomicity,
    collectives,
    control_loop,
    deadlock,
    donation,
    events,
    jit_hygiene,
    jit_tracking,
    locks,
    lockset,
    metrics,
    objectref,
    reentrancy,
    sharding_axis,
    splitphase,
)
