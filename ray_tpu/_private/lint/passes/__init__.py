"""Built-in graftlint passes. Importing this package registers them."""

from ray_tpu._private.lint.passes import (  # noqa: F401
    async_blocking,
    collectives,
    control_loop,
    deadlock,
    donation,
    events,
    jit_hygiene,
    locks,
    metrics,
    objectref,
    sharding_axis,
    splitphase,
)
