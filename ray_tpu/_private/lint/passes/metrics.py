"""metric-declarations: the metric-registry contract, as a graftlint
pass.

Grown across PRs 2–5 as ``scripts/check_metrics.py`` and migrated here
verbatim in behavior; the script remains as a thin shim over
:func:`check_paths`. See that module's history for the rationale of
each rule:

- names are snake_case and don't pre-carry the ``rtpu_`` prefix;
- framework metrics belong to a registered family prefix;
- histograms end in ``_seconds``/``_bytes``;
- gauges must not declare a ``pid`` tag key;
- names ending ``_ratio`` must be Gauges (a ratio is a point-in-time
  fraction; a ``_ratio`` Counter sums into nonsense);
- redeclarations agree on type/tag_keys/boundaries (cross-file — the
  runtime registry only catches collisions that co-execute in one
  process);
- hand-rolled Prometheus exposition (``# TYPE`` lines inside string
  literals) reserves ``_total`` for counters and requires it of them;
- declared tag keys must not be unbounded identifiers (tenant, model,
  request_id, ...) — per-entity attribution routes through the
  accounting plane's bounded fold (observability/accounting.py), the
  only module exempt from the rule.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional

from ray_tpu._private.lint.core import (
    Finding, LintPass, ModuleInfo, register, run_lint,
)

_METRIC_CLASSES = ("Counter", "Gauge", "Histogram")
_METRICS_MODULE = "ray_tpu.util.metrics"
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Registered metric families: every metric the framework itself declares
# must start with one of these (exported as rtpu_<family>...). New
# subsystems add their prefix here — one reviewable place instead of
# ad-hoc names scattered over /metrics.
_FAMILIES = (
    "collective_",    # util.collective op/bytes/latency (collective.py)
    "ctrl_",          # control-plane decision counters (control.py)
    "data_",          # Dataset pipeline stages (stats.py / executors)
    "device_",        # accelerator HBM / device-count gauges
    "jit_",           # tracked_jit compile/trace telemetry
    "learner_",       # RLlib learner update metrics
    "node_",          # raylet reporter node gauges
    "object_store_",  # per-node store pressure (spill/evict/pin)
    "rl_",            # decoupled-RL podracer plane (observability/rl.py)
    "sched_",         # scheduling-latency phase breakdown (profiling.py)
    "serve_",         # LLM serving latency/queue metrics
    "train_",         # train-session report metrics
    "worker_",        # per-worker process gauges
    "xla_",           # program cost/roofline attribution (xla.py)
)

_EXPOSITION_TYPE_RE = re.compile(
    r"#\s*TYPE\s+([a-zA-Z_:][a-zA-Z0-9_:]*)\s+"
    r"(counter|gauge|histogram|summary)\b")

# Tag keys whose value space is an unbounded identifier: each distinct
# value mints a new Prometheus series, so a declared label of this shape
# is a cardinality bomb. Per-tenant/per-model attribution belongs in the
# accounting plane (observability/accounting.py), whose TenantLedger
# folds rows into a bounded top-N before anything reaches a label.
# (trace_id is excluded here: the metric-exemplar-tag rule owns it.)
_UNBOUNDED_TAGS = ("tenant", "model", "request_id", "user", "user_id",
                   "session_id", "job_id", "task_id", "actor_id",
                   "object_id")

# Emit sites allowed to carry unbounded-id labels: the accounting plane
# bounds them (max_tenants fold + __other__ overflow) before export.
_CARDINALITY_EXEMPT_SUFFIXES = ("observability/accounting.py",)


def _metric_bindings(tree: ast.Module) -> Dict[str, str]:
    """local name -> metric class, for names imported from the metrics
    module (``from ray_tpu.util.metrics import Counter [as C]``)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and \
                node.module == _METRICS_MODULE:
            for alias in node.names:
                if alias.name in _METRIC_CLASSES:
                    out[alias.asname or alias.name] = alias.name
    return out


def _module_aliases(tree: ast.Module) -> List[str]:
    """Names the metrics *module* is bound to (``import
    ray_tpu.util.metrics [as m]`` / ``from ray_tpu.util import
    metrics``) — calls like ``m.Counter(...)`` count too."""
    out: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _METRICS_MODULE:
                    out.append(alias.asname or "ray_tpu")
        elif isinstance(node, ast.ImportFrom) and \
                node.module == "ray_tpu.util":
            for alias in node.names:
                if alias.name == "metrics":
                    out.append(alias.asname or "metrics")
    return out


def _call_metric_class(call: ast.Call, bindings: Dict[str, str],
                       mod_aliases: List[str]) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return bindings.get(f.id)
    if isinstance(f, ast.Attribute) and f.attr in _METRIC_CLASSES:
        # metrics.Counter(...) / ray_tpu.util.metrics.Counter(...)
        base = f.value
        if isinstance(base, ast.Name) and base.id in mod_aliases:
            return f.attr
        if (isinstance(base, ast.Attribute)
                and ast.unparse(base).endswith("util.metrics")):
            return f.attr
    return None


def _literal(node: Optional[ast.expr]) -> Any:
    """Literal value or None for dynamic expressions (dynamic names are
    reported as unlintable rather than guessed at)."""
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _norm(v: Any) -> Any:
    return tuple(v) if isinstance(v, (list, tuple)) else v


@register
class MetricsPass(LintPass):
    name = "metric-declarations"
    rules = ("metric-unlintable-name", "metric-name", "metric-family",
             "metric-histogram-suffix", "metric-gauge-pid-tag",
             "metric-redeclared", "metric-exposition",
             "metric-exemplar-tag", "metric-ratio-gauge",
             "metric-label-cardinality")
    description = ("metric naming/family/unit/tag contract + cross-file "
                   "redeclaration consistency + Prometheus exposition "
                   "suffix discipline (ex scripts/check_metrics.py)")

    def __init__(self):
        self._decls: List[Dict[str, Any]] = []

    def check_module(self, mod: ModuleInfo):
        out: List[Finding] = []
        bindings = _metric_bindings(mod.tree)
        mod_aliases = _module_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cls = _call_metric_class(node, bindings, mod_aliases)
            if cls is None:
                out.extend(self._check_exemplar_call(mod, node))
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            name_node = node.args[0] if node.args else kw.get("name")
            name = _literal(name_node)
            if not isinstance(name, str):
                out.append(mod.finding(
                    "metric-unlintable-name", node,
                    f"{cls} name is not a string literal — cannot lint"))
                continue
            self._decls.append({
                "mod": mod, "line": node.lineno,
                "where": f"{mod.relpath}:{node.lineno}",
                "class": cls, "name": name,
                "tag_keys": _literal(kw.get("tag_keys")),
                "boundaries": _literal(kw.get("boundaries")),
            })
            out.extend(self._check_decl(self._decls[-1]))
        out.extend(self._check_exposition(mod))
        return out

    def _check_decl(self, d: Dict[str, Any]):
        mod, line, name = d["mod"], d["line"], d["name"]
        if not _NAME_RE.match(name):
            yield mod.finding(
                "metric-name", line,
                f"metric name {name!r} is not snake_case "
                f"([a-z][a-z0-9_]*) — it would export badly as "
                f"rtpu_{name}")
        if name.startswith("rtpu_"):
            yield mod.finding(
                "metric-name", line,
                f"metric name {name!r} already carries the "
                f"rtpu_ prefix; the exporter adds it (would become "
                f"rtpu_rtpu_...)")
        if not name.startswith(_FAMILIES):
            yield mod.finding(
                "metric-family", line,
                f"metric name {name!r} is outside the "
                f"registered families {sorted(set(_FAMILIES))}; prefix it "
                f"with its subsystem family (or extend _FAMILIES in "
                f"ray_tpu/_private/lint/passes/metrics.py)")
        if name.endswith("_ratio") and d["class"] != "Gauge":
            yield mod.finding(
                "metric-ratio-gauge", line,
                f"{d['class'].lower()} {name!r} ends in _ratio but "
                f"ratios are point-in-time fractions — declare it a "
                f"Gauge (a _ratio counter accumulates into a "
                f"meaningless sum and rate() of it is garbage; a "
                f"_ratio histogram buckets a bounded [0,1] value "
                f"nobody quantiles)")
        if d["class"] == "Histogram" and \
                not name.endswith(("_seconds", "_bytes")):
            yield mod.finding(
                "metric-histogram-suffix", line,
                f"histogram {name!r} must end in _seconds "
                f"or _bytes — the unit suffix is how dashboards and "
                f"histogram_quantile() users know what the buckets "
                f"measure (https://prometheus.io/docs/practices/naming/)")
        tag_keys = d.get("tag_keys")
        if tag_keys and "trace_id" in tag_keys:
            yield mod.finding(
                "metric-exemplar-tag", line,
                f"metric {name!r} declares tag key 'trace_id' — "
                f"exemplar identity rides the dedicated "
                f"observe(..., trace_id=) kwarg and must not widen the "
                f"declared label set (per-trace labels are unbounded "
                f"cardinality)")
        if tag_keys and not mod.relpath.replace(
                "\\", "/").endswith(_CARDINALITY_EXEMPT_SUFFIXES):
            for t in tag_keys:
                if t in _UNBOUNDED_TAGS:
                    yield mod.finding(
                        "metric-label-cardinality", line,
                        f"metric {name!r} declares unbounded-id tag key "
                        f"{t!r} — each distinct value mints a new "
                        f"series; route per-{t} attribution through the "
                        f"accounting plane "
                        f"(ray_tpu/observability/accounting.py), whose "
                        f"TenantLedger folds rows into a bounded set "
                        f"before any label is emitted")
        if d["class"] == "Gauge" and tag_keys and "pid" in tag_keys:
            yield mod.finding(
                "metric-gauge-pid-tag", line,
                f"gauge {name!r} declares tag key 'pid' — "
                f"the exporter appends its own pid=<source> label to "
                f"every gauge and duplicate label names break the "
                f"Prometheus scrape")

    def _check_exemplar_call(self, mod: ModuleInfo, call: ast.Call):
        """``x.observe(v, tags={... "trace_id": ...})`` smuggles the
        exemplar identity into the label set; it belongs on the
        dedicated ``trace_id=`` kwarg (which records an exemplar
        instead of minting a per-trace series)."""
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "observe"):
            return
        for k in call.keywords:
            if k.arg != "tags":
                continue
            tags = _literal(k.value)
            if isinstance(tags, dict) and "trace_id" in tags:
                yield mod.finding(
                    "metric-exemplar-tag", call,
                    "observe() call passes 'trace_id' inside tags= — "
                    "use the observe(..., trace_id=) exemplar kwarg; "
                    "a trace_id label mints one series per request "
                    "and must not change the declared label set")

    def _check_exposition(self, mod: ModuleInfo):
        for m in _EXPOSITION_TYPE_RE.finditer(mod.src):
            name, kind = m.group(1), m.group(2)
            line = mod.src.count("\n", 0, m.start()) + 1
            if name.endswith("_total") and kind != "counter":
                yield mod.finding(
                    "metric-exposition", line,
                    f"exposition declares '# TYPE {name} "
                    f"{kind}' but the _total suffix is reserved for "
                    f"counters — clients rate() it into garbage")
            if kind == "counter" and not name.endswith("_total"):
                yield mod.finding(
                    "metric-exposition", line,
                    f"exposition declares counter {name!r} "
                    f"without the conventional _total suffix")

    def finalize(self):
        by_name: Dict[str, List[Dict[str, Any]]] = {}
        for d in self._decls:
            by_name.setdefault(d["name"], []).append(d)
        for name, group in sorted(by_name.items()):
            first = group[0]
            for other in group[1:]:
                for field in ("class", "tag_keys", "boundaries"):
                    a = first.get(field)
                    b = other.get(field)
                    if _norm(a) != _norm(b):
                        yield other["mod"].finding(
                            "metric-redeclared", other["line"],
                            f"metric {name!r} redeclared "
                            f"with different {field} ({_norm(b)!r}) than "
                            f"{first['where']} ({_norm(a)!r}) — the "
                            f"runtime registry raises on this collision")


# ------------------------------------------------------- script-shim API

def check_exposition_text(src: str, where: str) -> List[str]:
    """Lint hand-rolled Prometheus exposition blocks in raw source text:
    the ``_total`` suffix is reserved for counters and required of them
    (https://prometheus.io/docs/practices/naming/)."""
    problems: List[str] = []
    for m in _EXPOSITION_TYPE_RE.finditer(src):
        name, kind = m.group(1), m.group(2)
        line = src.count("\n", 0, m.start()) + 1
        if name.endswith("_total") and kind != "counter":
            problems.append(
                f"{where}:{line}: exposition declares '# TYPE {name} "
                f"{kind}' but the _total suffix is reserved for "
                f"counters — clients rate() it into garbage")
        if kind == "counter" and not name.endswith("_total"):
            problems.append(
                f"{where}:{line}: exposition declares counter {name!r} "
                f"without the conventional _total suffix")
    return problems


def check_paths(root: str) -> List[str]:
    """Historical ``scripts/check_metrics.py`` entry point: lint every
    .py under ``root`` with the metrics pass only; returns violation
    strings formatted ``path:line: message``."""
    result = run_lint([root], rel_to=None, passes=[MetricsPass()])
    return [f"{f.path}:{f.line}: {f.message}"
            for f in result.findings + result.baselined]
