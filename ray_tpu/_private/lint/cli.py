"""graftlint CLI: ``python -m ray_tpu._private.lint`` /
``scripts/graftlint.py``.

Exit status is 0 iff there are zero unbaselined, unsuppressed findings
(stale baseline entries are reported but don't fail — prune them with
``--baseline-update``). Run with ``--baseline-update`` after fixing or
justifying findings; it rewrites the baseline to exactly the current
finding set, preserving justifications of entries that still match.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def repo_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), ".graftlint-baseline.json")


def main(argv: Optional[List[str]] = None) -> int:
    from ray_tpu._private.lint import (
        Baseline, all_passes, registered_passes, run_lint,
    )

    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST static analysis for jit-hygiene, distributed-"
                    "deadlock, collective-consistency, lock-discipline, "
                    "async-blocking, metric and event-schema bugs.")
    parser.add_argument(
        "roots", nargs="*",
        help="files/directories to lint (default: the ray_tpu package)")
    parser.add_argument(
        "--select", action="append", default=None, metavar="PASS",
        help="run only this pass (repeatable; see --list-passes)")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: <repo>/.graftlint-baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding")
    parser.add_argument(
        "--baseline-update", action="store_true",
        help="rewrite the baseline to the current finding set "
             "(keeps justifications of entries that still match)")
    parser.add_argument(
        "--list-passes", action="store_true",
        help="list registered passes and their rules")
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="print findings only (no summary)")
    args = parser.parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.name}: {p.description}")
            for r in p.rules:
                print(f"    {r}")
        return 0

    root = repo_root()
    roots = args.roots or [os.path.join(root, "ray_tpu")]
    baseline_path = None if args.no_baseline else (
        args.baseline or default_baseline_path())

    result = run_lint(roots, select=args.select,
                      baseline=baseline_path, rel_to=root)

    if args.baseline_update:
        path = args.baseline or default_baseline_path()
        prev = Baseline.load(path if os.path.exists(path) else None)
        new_base = Baseline.from_findings(
            result.findings + result.baselined, previous=prev)
        new_base.save(path)
        print(f"graftlint: baseline written to {path} "
              f"({len(new_base.entries)} entries)")
        return 0

    for f in result.findings:
        print(f.render())
    for e in result.stale_baseline:
        print(f"graftlint: stale baseline entry (fixed? prune with "
              f"--baseline-update): {e['path']}: [{e['rule']}] "
              f"{e.get('context', '')!r}", file=sys.stderr)
    if result.findings:
        print(f"graftlint: {len(result.findings)} new finding(s) "
              f"({len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed)", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"graftlint: OK ({len(result.modules)} files, "
              f"{len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed, "
              f"{len(registered_passes())} passes)")
    return 0
