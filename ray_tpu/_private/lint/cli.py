"""graftlint CLI: ``python -m ray_tpu._private.lint`` /
``scripts/graftlint.py``.

Exit status is 0 iff there are zero unbaselined, unsuppressed findings
(stale baseline entries are reported but don't fail — drop just those
with ``--prune-baseline``). Run with ``--baseline-update`` after fixing
or justifying findings; it rewrites the baseline to exactly the current
finding set, preserving justifications of entries that still match.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional


def repo_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), ".graftlint-baseline.json")


def changed_files(base: str, root: str) -> Optional[List[str]]:
    """Python files changed vs ``base`` (plus untracked ones), absolute
    paths; None when git can't answer (not a repo, bad ref)."""
    names = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", base, "--"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            print(f"graftlint: --changed-only: {' '.join(cmd[3:])} "
                  f"failed: {proc.stderr.strip()}", file=sys.stderr)
            return None
        names.update(proc.stdout.splitlines())
    return [os.path.join(root, n) for n in sorted(names)
            if n.endswith(".py")]


def _under(path: str, roots: List[str]) -> bool:
    path = os.path.abspath(path)
    for r in roots:
        r = os.path.abspath(r)
        if path == r or path.startswith(r.rstrip(os.sep) + os.sep):
            return True
    return False


def _sarif(result) -> dict:
    """The finding list as a SARIF 2.1.0 log — same records as
    ``--format=json``, reshaped for code-scanning UIs."""
    from ray_tpu._private.lint import all_passes

    rule_to_pass = {r: p for p in all_passes() for r in p.rules}
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": f.line},
                },
            }],
        })
    rules = [{
        "id": rule,
        "shortDescription": {
            "text": rule_to_pass[rule].description
            if rule in rule_to_pass else rule},
    } for rule in sorted({f.rule for f in result.findings})]
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    from ray_tpu._private.lint import (
        Baseline, all_passes, registered_passes, run_lint,
    )

    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST static analysis for jit-hygiene, distributed-"
                    "deadlock, collective-consistency, lock-discipline, "
                    "async-blocking, metric and event-schema bugs.")
    parser.add_argument(
        "roots", nargs="*",
        help="files/directories to lint (default: the ray_tpu package)")
    parser.add_argument(
        "--select", action="append", default=None, metavar="PASS",
        help="run only this pass (repeatable; see --list-passes)")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: <repo>/.graftlint-baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding")
    parser.add_argument(
        "--baseline-update", action="store_true",
        help="rewrite the baseline to the current finding set "
             "(keeps justifications of entries that still match)")
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="drop baseline entries nothing matches anymore (fixed or "
             "moved code) without grandfathering any new findings")
    parser.add_argument(
        "--list-passes", action="store_true",
        help="list registered passes and their rules")
    parser.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None,
        metavar="BASE",
        help="lint only .py files changed vs BASE (git diff "
             "--name-only; default base: HEAD) plus untracked files")
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (json: one machine-readable object; sarif: "
             "SARIF 2.1.0 for code-scanning UIs)")
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="print findings only (no summary)")
    args = parser.parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.name}: {p.description}")
            for r in p.rules:
                print(f"    {r}")
        return 0

    root = repo_root()
    roots = args.roots or [os.path.join(root, "ray_tpu")]
    baseline_path = None if args.no_baseline else (
        args.baseline or default_baseline_path())

    partial = False
    if args.changed_only is not None:
        changed = changed_files(args.changed_only, root)
        if changed is None:
            # Outside a work tree (tarball checkout, exported CI dir)
            # there is no diff to narrow by: lint everything instead of
            # failing a gate that has nothing to do with git.
            print("graftlint: --changed-only: git can't answer here; "
                  "falling back to a full scan", file=sys.stderr)
        else:
            partial = True
            roots = [f for f in changed
                     if _under(f, roots) and os.path.exists(f)]

    result = run_lint(roots, select=args.select,
                      baseline=baseline_path, rel_to=root)
    if partial:
        # A partial run can't tell fixed-elsewhere from out-of-scope.
        result.stale_baseline = []

    if args.prune_baseline:
        if partial or args.no_baseline or args.select or args.roots:
            print("graftlint: --prune-baseline needs a full unfiltered "
                  "run (no roots, --changed-only, --no-baseline or "
                  "--select): a partial run can't tell a fixed finding "
                  "from an unscanned one", file=sys.stderr)
            return 2
        path = args.baseline or default_baseline_path()
        prev = Baseline.load(path if os.path.exists(path) else None)
        new_base = Baseline.from_findings(result.baselined, previous=prev)
        new_base.save(path)
        pruned = len(prev.entries) - len(new_base.entries)
        print(f"graftlint: baseline pruned: {pruned} stale entries "
              f"removed, {len(new_base.entries)} kept ({path})")
        return 0

    if args.baseline_update:
        path = args.baseline or default_baseline_path()
        prev = Baseline.load(path if os.path.exists(path) else None)
        new_base = Baseline.from_findings(
            result.findings + result.baselined, previous=prev)
        new_base.save(path)
        print(f"graftlint: baseline written to {path} "
              f"({len(new_base.entries)} entries)")
        return 0

    if args.format == "sarif":
        print(json.dumps(_sarif(result), indent=2, sort_keys=True))
        return 1 if result.findings else 0

    if args.format == "json":
        def _row(f):
            return {"rule": f.rule, "path": f.path, "line": f.line,
                    "message": f.message, "context": f.context}
        print(json.dumps({
            "ok": not result.findings,
            "files": len(result.modules),
            "findings": [_row(f) for f in result.findings],
            "baselined": len(result.baselined),
            "suppressed": len(result.suppressed),
            "stale_baseline": result.stale_baseline,
        }, indent=2, sort_keys=True))
        return 1 if result.findings else 0

    for f in result.findings:
        print(f.render())
    for e in result.stale_baseline:
        print(f"graftlint: stale baseline entry (fixed? prune with "
              f"--baseline-update): {e['path']}: [{e['rule']}] "
              f"{e.get('context', '')!r}", file=sys.stderr)
    if result.findings:
        print(f"graftlint: {len(result.findings)} new finding(s) "
              f"({len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed)", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"graftlint: OK ({len(result.modules)} files, "
              f"{len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed, "
              f"{len(registered_passes())} passes)")
    return 0
