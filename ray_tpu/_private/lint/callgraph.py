"""Package-wide call graph for graftlint's interprocedural summaries.

One level of "what does the callee do" is enough for every consumer in
this suite: does the callee *block* (async-blocking-transitive), does it
*consume* its argument (a ``_wait_rs`` closure waiting a split-phase
handle, a wrapper whose param flows into a donated jit position), does
it *produce* an obligation (a ``_start_rs`` closure returning a ring
handle).  The graph therefore only needs call-site → function-def
resolution, not a sound points-to analysis; anything ambiguous resolves
to nothing and the client pass stays silent (precision over recall —
a lint that cries wolf gets deleted).

Resolution covers the shapes this codebase actually uses:

- bare names: lexically enclosing defs first (closures), then
  module-level defs, then ``from x import y`` (chased through up to 4
  re-export hops for package ``__init__`` files — the bound also breaks
  re-export *cycles*, which would otherwise recurse forever);
- ``self.m()`` / ``cls.m()``: methods of the lexically enclosing class,
  walking the base-class chain (local and imported bases) when the
  class itself does not define the method;
- ``ClassName.m()`` and ``alias.m()`` for imported modules.

The graph is cached per ``run_lint`` module set: several passes share
one build.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu._private.lint._ast_util import dotted
from ray_tpu._private.lint.core import ModuleInfo

__all__ = ["FuncInfo", "CallGraph", "get_call_graph"]

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class FuncInfo:
    """One function/method definition."""

    __slots__ = ("node", "mod", "name", "cls", "parent", "depth")

    def __init__(self, node, mod: ModuleInfo, cls: str,
                 parent: Optional["FuncInfo"], depth: int):
        self.node = node
        self.mod = mod
        self.name = node.name
        self.cls = cls              # enclosing class name, "" if none
        self.parent = parent        # enclosing function, None at top
        self.depth = depth

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    @property
    def qualname(self) -> str:
        parts = []
        f: Optional[FuncInfo] = self
        while f is not None:
            parts.append(f.name)
            f = f.parent
        if self.cls:
            parts.append(self.cls)
        return ".".join(reversed(parts))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<func {self.mod.relpath}:{self.qualname}>"


def _module_name(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[:-len(".__init__")]
    return name


class CallGraph:
    def __init__(self, mods: Sequence[ModuleInfo]):
        self.funcs: List[FuncInfo] = []
        self.by_node: Dict[int, FuncInfo] = {}
        self._mod_by_name: Dict[str, ModuleInfo] = {}
        # per module: visible defs, class methods, import aliases
        self._defs: Dict[str, Dict[str, List[FuncInfo]]] = {}
        self._methods: Dict[str, Dict[str, Dict[str, FuncInfo]]] = {}
        self._bases: Dict[str, Dict[str, List[str]]] = {}
        self._imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        for mod in mods:
            self._mod_by_name[_module_name(mod.relpath)] = mod
        for mod in mods:
            self._index_module(mod)

    # ------------------------------------------------------------ build

    def _index_module(self, mod: ModuleInfo) -> None:
        defs: Dict[str, List[FuncInfo]] = {}
        methods: Dict[str, Dict[str, FuncInfo]] = {}
        bases: Dict[str, List[str]] = {}
        imports: Dict[str, Tuple[str, Optional[str]]] = {}
        modname = _module_name(mod.relpath)

        def visit(node, cls: str, parent: Optional[FuncInfo],
                  depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    methods.setdefault(child.name, {})
                    bases[child.name] = [
                        d for d in (dotted(b) for b in child.bases) if d]
                    visit(child, child.name, parent, depth)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fi = FuncInfo(child, mod, cls, parent, depth)
                    self.funcs.append(fi)
                    self.by_node[id(child)] = fi
                    defs.setdefault(child.name, []).append(fi)
                    if cls:
                        methods.setdefault(cls, {}).setdefault(
                            child.name, fi)
                    visit(child, "", fi, depth + 1)
                else:
                    visit(child, cls, parent, depth)

        visit(mod.tree, "", None, 0)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name, None)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = modname.split(".")
                    # level 1 = this module's package, 2 = its parent...
                    pkg = pkg[:len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module]
                                           if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imports[alias.asname or alias.name] = (base,
                                                           alias.name)
        self._defs[mod.relpath] = defs
        self._methods[mod.relpath] = methods
        self._bases[mod.relpath] = bases
        self._imports[mod.relpath] = imports

    # ---------------------------------------------------------- resolve

    def resolve(self, func_expr: ast.expr,
                caller: Optional[FuncInfo],
                mod: ModuleInfo,
                _depth: int = 0) -> Optional[FuncInfo]:
        """The FuncInfo a call target refers to, or None when ambiguous
        or out of view."""
        if _depth > 4:
            return None
        if isinstance(func_expr, ast.Name):
            return self._resolve_name(func_expr.id, caller, mod, _depth)
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            attr = func_expr.attr
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and caller is not None \
                        and caller.cls:
                    return self._method_in_class(mod.relpath, caller.cls,
                                                 attr)
                # ClassName.m() on a locally defined class.
                if base.id in self._methods.get(mod.relpath, {}):
                    return self._method_in_class(mod.relpath, base.id,
                                                 attr)
                # module-alias.f()
                imp = self._imports[mod.relpath].get(base.id)
                if imp is not None:
                    target = imp[0] if imp[1] is None else \
                        f"{imp[0]}.{imp[1]}"
                    return self._resolve_in_module(target, attr, _depth)
        return None

    def _resolve_name(self, name: str, caller: Optional[FuncInfo],
                      mod: ModuleInfo, _depth: int) -> Optional[FuncInfo]:
        cands = self._defs.get(mod.relpath, {}).get(name, [])
        if caller is not None and len(cands) > 1:
            # Prefer the def lexically closest to the caller: one whose
            # enclosing-function chain is a prefix of the caller's.
            chain = set()
            f: Optional[FuncInfo] = caller
            while f is not None:
                chain.add(id(f.node))
                f = f.parent
            near = [c for c in cands
                    if c.parent is None or id(c.parent.node) in chain
                    or (caller.parent is not None and c.parent is
                        caller.parent)]
            if len(near) == 1:
                return near[0]
            cands = near or cands
        if len(cands) == 1:
            return cands[0]
        if cands:
            return None   # ambiguous: stay silent
        imp = self._imports.get(mod.relpath, {}).get(name)
        if imp is not None and imp[1] is not None:
            return self._resolve_in_module(imp[0], imp[1], _depth)
        return None

    def _method_in_class(self, relpath: str, cls: str, attr: str,
                         _seen: Optional[set] = None) -> Optional[FuncInfo]:
        """``cls.attr`` in the class itself, else MRO-style through its
        base classes (local first, then imported), cycle-safe."""
        if _seen is None:
            _seen = set()
        if (relpath, cls) in _seen or len(_seen) > 8:
            return None
        _seen.add((relpath, cls))
        hit = self._methods.get(relpath, {}).get(cls, {}).get(attr)
        if hit is not None:
            return hit
        for base in self._bases.get(relpath, {}).get(cls, []):
            head, _, tail = base.partition(".")
            if not tail and head in self._methods.get(relpath, {}):
                hit = self._method_in_class(relpath, head, attr, _seen)
            else:
                # Imported base: ``from x import Base`` or ``mod.Base``.
                imp = self._imports.get(relpath, {}).get(head)
                if imp is None:
                    continue
                if tail:          # module alias . ClassName
                    modname, clsname = (imp[0] if imp[1] is None
                                        else f"{imp[0]}.{imp[1]}"), tail
                else:             # from module import ClassName
                    if imp[1] is None:
                        continue
                    modname, clsname = imp
                target = self._mod_by_name.get(modname)
                if target is None:
                    continue
                hit = self._method_in_class(target.relpath, clsname,
                                            attr, _seen)
            if hit is not None:
                return hit
        return None

    def _resolve_in_module(self, modname: str, attr: str,
                           _depth: int) -> Optional[FuncInfo]:
        if _depth > 4:            # re-export chain too deep (or a cycle)
            return None
        target = self._mod_by_name.get(modname)
        if target is None:
            return None
        cands = [c for c in
                 self._defs.get(target.relpath, {}).get(attr, [])
                 if c.parent is None and not c.cls]
        if len(cands) == 1:
            return cands[0]
        if cands:
            return None
        # Chase one re-export hop (package __init__ files).
        imp = self._imports.get(target.relpath, {}).get(attr)
        if imp is not None and imp[1] is not None:
            return self._resolve_in_module(imp[0], imp[1], _depth + 1)
        return None

    # ---------------------------------------------------------- queries

    def direct_calls(self, func: FuncInfo
                     ) -> Iterable[Tuple[ast.Call, Optional[FuncInfo]]]:
        """(call node, resolved callee) for every call in the function's
        own scope (nested defs/lambdas excluded — they run elsewhere)."""
        stack = list(ast.iter_child_nodes(func.node))
        while stack:
            n = stack.pop()
            if isinstance(n, _SCOPE_NODES):
                continue
            if isinstance(n, ast.Call):
                yield n, self.resolve(n.func, func, func.mod)
            stack.extend(ast.iter_child_nodes(n))


_graph_cache: List[Tuple[Tuple[int, ...], CallGraph]] = []


def get_call_graph(mods: Sequence[ModuleInfo]) -> CallGraph:
    """Build (or reuse) the call graph for this run's module set.  Keyed
    by object identity: within one ``run_lint`` every pass sees the same
    ModuleInfo instances."""
    key = tuple(id(m) for m in mods)
    for k, g in _graph_cache:
        if k == key:
            return g
    g = CallGraph(mods)
    _graph_cache.append((key, g))
    del _graph_cache[:-4]
    return g
