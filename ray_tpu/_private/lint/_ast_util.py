"""Shared AST helpers for graftlint passes."""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node: ast.expr) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else (a
    subscripted/called base still yields its attribute tail, so
    ``x[0].recv`` -> ``.recv`` and membership checks on suffixes keep
    working)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("")
    else:
        return ""
    return ".".join(reversed(parts))


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def walk_scope(fn: ast.AST, skip_nested: bool = True) -> Iterator[ast.AST]:
    """Yield descendants of ``fn``; with ``skip_nested`` the walk does
    not descend into nested def/async-def/lambda scopes (their bodies
    run under different execution rules — e.g. a run_in_executor lambda
    inside an async def is *supposed* to block)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if skip_nested and isinstance(node, _SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def awaited_calls(tree: ast.AST) -> Set[int]:
    """ids of Call nodes that sit directly under an ``await``."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


def consumed_calls(tree: ast.AST) -> Set[int]:
    """ids of Call nodes that are *consumed* by an enclosing await or
    call expression — ``await asyncio.wait_for(ev.wait(), t)`` never
    executes ``ev.wait`` synchronously (it builds a coroutine/argument
    for the wrapper), so wait-ish rules must not flag it."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        inner: Iterator[ast.AST] = ()
        if isinstance(node, ast.Await):
            inner = ast.walk(node.value)
        elif isinstance(node, ast.Call):
            args = list(node.args) + [kw.value for kw in node.keywords]
            inner = (n for a in args for n in ast.walk(a))
        for sub in inner:
            if isinstance(sub, ast.Call):
                out.add(id(sub))
    return out


def lockish(expr: ast.expr) -> Optional[str]:
    """The dotted text of a lock-looking expression (``self._lock``,
    ``module._STATE_MUTEX``, a ``Condition``) or None. One definition of
    "what counts as a lock" shared by the race passes, so a finding from
    one pass and a protection claim from another never disagree."""
    try:
        text = ast.unparse(expr)
    except Exception:
        return None
    base = text.split("(")[0].strip()
    low = base.lower()
    if "lock" in low or "mutex" in low or "cond" in low:
        return base
    return None


def literal(node: Optional[ast.expr]):
    """ast.literal_eval or None for dynamic expressions."""
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def has_timeout(call: ast.Call) -> bool:
    """True when the call passes any ``timeout``-ish argument."""
    for kw in call.keywords:
        if kw.arg and ("timeout" in kw.arg or kw.arg == "deadline"):
            return True
    return False


def enclosing_class_map(tree: ast.Module):
    """function/method def -> enclosing ClassDef name ('' at module
    level), plus {class name: ClassDef}."""
    owner = {}
    classes = {}

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                classes[child.name] = child
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner[child] = cls
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, "")
    return owner, classes
