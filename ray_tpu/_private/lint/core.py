"""graftlint framework: findings, pass registry, suppressions, baseline.

Everything here is pure stdlib + ``ast``; passes never import (let
alone execute) the code under analysis, so linting a file with a
syntax-level hazard cannot run it.

Suppression grammar (scanned from raw source, so it works inside any
statement the AST attributes to that line):

- ``expr  # graftlint: disable=rule-a,rule-b`` — suppress those rules
  on this line (a pass name suppresses every rule the pass owns;
  ``all`` suppresses everything).
- ``# graftlint: disable-file=rule-a`` on a line of its own — suppress
  for the whole file.

Baseline file: JSON with one entry per grandfathered finding, matched
by ``(rule, path, context)`` where context is the stripped source line
— line-number independent, so unrelated edits above a grandfathered
finding don't resurrect it. Every entry carries a human-written
``justification``; ``--baseline-update`` preserves justifications of
entries that still match and stamps new ones with ``TODO: justify``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

__all__ = [
    "Finding", "ModuleInfo", "LintPass", "LintResult", "Baseline",
    "register", "registered_passes", "all_passes", "iter_modules",
    "run_lint",
]


@dataclass
class Finding:
    """One violation: a rule id, a location, and a message.

    ``context`` (the stripped source line) is the stable half of the
    identity used for baseline matching; ``line`` is for humans.
    """

    rule: str
    path: str          # repo-relative (or as-given when rel_to=None)
    line: int
    message: str
    context: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)


@dataclass
class ModuleInfo:
    """A parsed source file handed to every pass."""

    path: str          # absolute on disk
    relpath: str       # as reported in findings
    src: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def context_for(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=rule, path=self.relpath, line=int(line),
                       message=message,
                       context=self.context_for(int(line)))


class LintPass:
    """Base class for graftlint passes.

    Subclasses set ``name`` (kebab-case pass id), ``rules`` (the rule
    ids they may emit — used by ``--list-passes`` and suppression-by-
    pass-name), and ``description``. Per-file logic goes in
    :meth:`check_module`; cross-file logic (consistency tables, lock
    graphs) accumulates state in :meth:`check_module` and reports from
    :meth:`finalize`. A fresh instance is built per run, so instance
    state never leaks across runs.
    """

    name: str = ""
    rules: Sequence[str] = ()
    description: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[LintPass]] = {}


def register(cls: Type[LintPass]) -> Type[LintPass]:
    if not cls.name:
        raise ValueError(f"{cls.__name__} has no pass name")
    if cls.name in _REGISTRY and _REGISTRY[cls.name] is not cls:
        raise ValueError(f"duplicate graftlint pass {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def registered_passes() -> Dict[str, Type[LintPass]]:
    return dict(_REGISTRY)


def all_passes(select: Optional[Sequence[str]] = None) -> List[LintPass]:
    """Fresh instances of the selected (default: all) passes."""
    if select:
        unknown = sorted(set(select) - set(_REGISTRY))
        if unknown:
            raise ValueError(
                f"unknown pass(es) {unknown}; registered: "
                f"{sorted(_REGISTRY)}")
        names = [n for n in sorted(_REGISTRY) if n in set(select)]
    else:
        names = sorted(_REGISTRY)
    return [_REGISTRY[n]() for n in names]


# --------------------------------------------------------------- modules

def iter_modules(roots: Sequence[str],
                 rel_to: Optional[str] = None,
                 exclude_dirs: Sequence[str] = ("__pycache__",),
                 ) -> List[ModuleInfo]:
    """Parse every ``.py`` under ``roots`` (a file path is taken as-is).

    Files that fail to parse are skipped here; the runner reports them
    as ``parse-error`` findings so a broken file can't silently dodge
    the lint.
    """
    mods: List[ModuleInfo] = []
    seen = set()
    for root in roots:
        paths: List[str] = []
        if os.path.isfile(root):
            paths.append(root)
        else:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in exclude_dirs)
                paths.extend(os.path.join(dirpath, fn)
                             for fn in sorted(filenames)
                             if fn.endswith(".py"))
        for path in paths:
            apath = os.path.abspath(path)
            if apath in seen:
                continue
            seen.add(apath)
            with open(apath, "r", encoding="utf-8") as f:
                src = f.read()
            rel = (os.path.relpath(apath, rel_to).replace(os.sep, "/")
                   if rel_to else path)
            try:
                tree = ast.parse(src, filename=apath)
            except SyntaxError as e:
                # A ModuleInfo with an empty tree + a marker the runner
                # turns into a parse-error finding.
                tree = ast.Module(body=[], type_ignores=[])
                mods.append(ModuleInfo(
                    path=apath, relpath=rel, src=src, tree=tree,
                    lines=src.splitlines()))
                mods[-1].parse_error = e  # type: ignore[attr-defined]
                continue
            mods.append(ModuleInfo(path=apath, relpath=rel, src=src,
                                   tree=tree, lines=src.splitlines()))
    return mods


# ----------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([a-zA-Z0-9_,\-\s]+)")


def parse_suppressions(src: str):
    """Returns ``(line -> set(rules), set(file_rules))``."""
    per_line: Dict[int, set] = {}
    per_file: set = set()
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            per_file |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, per_file


def _suppressed(finding: Finding, per_line, per_file,
                rule_owner: Dict[str, str]) -> bool:
    names = {finding.rule, rule_owner.get(finding.rule, ""), "all"}
    if per_file & names:
        return True
    return bool(per_line.get(finding.line, set()) & names)


# -------------------------------------------------------------- baseline

class Baseline:
    """Grandfathered findings, matched multiset-wise by
    ``(rule, path, context)``."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries: List[dict] = list(entries or [])

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def save(self, path: str) -> None:
        data = {
            "// about": "graftlint baseline: grandfathered findings. "
                        "Matched by (rule, path, context); every entry "
                        "needs a justification. Regenerate with "
                        "scripts/graftlint.py --baseline-update.",
            "version": 1,
            "findings": sorted(
                self.entries,
                key=lambda e: (e["path"], e["rule"], e["context"])),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    def partition(self, findings: Sequence[Finding]):
        """Split findings into (new, grandfathered); also returns the
        stale baseline entries nothing matched (fixed or moved code —
        prune them with --baseline-update)."""
        pool: Dict[Tuple[str, str, str], List[dict]] = {}
        for e in self.entries:
            key = (e.get("rule", ""), e.get("path", ""),
                   e.get("context", ""))
            pool.setdefault(key, []).append(e)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            bucket = pool.get(f.baseline_key())
            if bucket:
                bucket.pop()
                old.append(f)
            else:
                new.append(f)
        stale = [e for bucket in pool.values() for e in bucket]
        return new, old, stale

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      previous: Optional["Baseline"] = None) -> "Baseline":
        """Baseline covering ``findings``, keeping justifications from
        ``previous`` for entries that still match."""
        just: Dict[Tuple[str, str, str], List[str]] = {}
        for e in (previous.entries if previous else []):
            key = (e.get("rule", ""), e.get("path", ""),
                   e.get("context", ""))
            just.setdefault(key, []).append(
                e.get("justification", "TODO: justify"))
        entries = []
        for f in findings:
            bucket = just.get(f.baseline_key())
            entries.append({
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "justification": (bucket.pop(0) if bucket
                                  else "TODO: justify"),
            })
        return cls(entries)


# ---------------------------------------------------------------- runner

@dataclass
class LintResult:
    findings: List[Finding]        # new, unbaselined, unsuppressed
    baselined: List[Finding]       # matched a baseline entry
    suppressed: List[Finding]      # killed by a suppression comment
    stale_baseline: List[dict]     # baseline entries nothing matched
    modules: List[ModuleInfo]

    @property
    def ok(self) -> bool:
        return not self.findings


def run_lint(roots: Sequence[str],
             select: Optional[Sequence[str]] = None,
             baseline: Optional[str] = None,
             rel_to: Optional[str] = None,
             passes: Optional[Sequence[LintPass]] = None) -> LintResult:
    """Run the selected passes over ``roots``.

    ``rel_to`` makes finding paths (and baseline keys) relative — the
    repo-root invocation passes the repo root so the baseline file is
    machine-independent. ``passes`` overrides the registry (tests).
    """
    mods = iter_modules(roots, rel_to=rel_to)
    active = list(passes) if passes is not None else all_passes(select)
    rule_owner: Dict[str, str] = {}
    for p in active:
        for r in p.rules:
            rule_owner[r] = p.name

    raw: List[Finding] = []
    for mod in mods:
        err = getattr(mod, "parse_error", None)
        if err is not None:
            raw.append(Finding(
                rule="parse-error", path=mod.relpath,
                line=getattr(err, "lineno", 0) or 0,
                message=f"file does not parse: {err.msg}",
                context=""))
            continue
        for p in active:
            raw.extend(p.check_module(mod))
    for p in active:
        raw.extend(p.finalize())

    supp_cache = {m.relpath: parse_suppressions(m.src) for m in mods}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        per_line, per_file = supp_cache.get(f.path, ({}, set()))
        if _suppressed(f, per_line, per_file, rule_owner):
            suppressed.append(f)
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))

    base = Baseline.load(baseline)
    new, old, stale = base.partition(kept)
    return LintResult(findings=new, baselined=old, suppressed=suppressed,
                      stale_baseline=stale, modules=mods)
