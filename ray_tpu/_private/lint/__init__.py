"""graftlint: project-wide static analysis for jit-hygiene, distributed
deadlocks, collective consistency, lock discipline, async-blocking
calls, metric declarations and the cluster-event schema.

The costliest bug classes in a TPU-native stack only bite at pod scale:
a silent recompile from an unhashable static arg burns minutes of XLA
compile time, a blocking call wedges an RPC event loop, divergent
collective sequences across replicas hang the whole mesh. None of them
fail a unit test. graftlint is the AST-level gate that catches them at
review time instead of in a pod postmortem.

Architecture:

- :mod:`ray_tpu._private.lint.core` — the framework: :class:`Finding`,
  :class:`ModuleInfo`, the pass registry, per-line / per-file
  suppression comments (``# graftlint: disable=<rule>``), the baseline
  file for grandfathered findings, and :func:`run_lint`.
- :mod:`ray_tpu._private.lint.passes` — the passes. Importing it
  registers every built-in pass.
- :mod:`ray_tpu._private.lint.cli` — ``python -m ray_tpu._private.lint``
  (also reachable as ``scripts/graftlint.py``).

Adding a pass: subclass :class:`~ray_tpu._private.lint.core.LintPass`
in a new module under ``passes/``, decorate it with ``@register``, and
import the module from ``passes/__init__``. See README "Static
analysis".
"""

from ray_tpu._private.lint.core import (  # noqa: F401
    Baseline,
    Finding,
    LintPass,
    LintResult,
    ModuleInfo,
    all_passes,
    iter_modules,
    register,
    registered_passes,
    run_lint,
)

# Importing the passes package registers every built-in pass.
from ray_tpu._private.lint import passes  # noqa: F401, E402

__all__ = [
    "Baseline",
    "Finding",
    "LintPass",
    "LintResult",
    "ModuleInfo",
    "all_passes",
    "iter_modules",
    "register",
    "registered_passes",
    "run_lint",
]
