import sys

from ray_tpu._private.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())
