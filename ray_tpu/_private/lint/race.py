"""Shared self-attribute access model for the graftrace race passes.

The await-atomicity and lockset-consistency passes both reason about
the same primitive events: "this statement reads ``self.<attr>``",
"this statement may modify ``self.<attr>``" (directly, through a
subscript/field store, or via a mutating container method), and "this
statement calls ``self.<m>()``".  One definition lives here so both
passes agree on what an access *is* — a write the atomicity pass acts
on is exactly a write the lockset pass would classify.

Everything operates on a CFG block statement's *effective extent*
(:func:`dataflow.effective_roots`): a ``for`` head contributes its
iterable, never its body, so per-statement events line up with the
program points the solver visits.
"""

from __future__ import annotations

import ast
from typing import Optional, Set

from ray_tpu._private.lint.dataflow import effective_roots, walk_no_scope

__all__ = [
    "MUTATORS", "self_base_attr", "stmt_self_writes", "stmt_self_reads",
    "stmt_self_calls", "fn_self_writes", "fn_self_accesses",
]

# Receiver methods that modify the receiver in place. A call
# ``self._pending.append(x)`` is a *write* to ``_pending`` for race
# purposes even though no store node exists.
MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
}


def self_base_attr(node: ast.AST) -> Optional[str]:
    """The ``self`` attribute an lvalue-ish expression is rooted in:
    ``self._depth[r]`` -> ``_depth``, ``self._state.params`` ->
    ``_state``, plain ``x[k]`` -> None."""
    while isinstance(node, (ast.Subscript, ast.Starred)):
        node = node.value
    last = None
    while isinstance(node, ast.Attribute):
        last = node
        node = node.value
        while isinstance(node, ast.Subscript):
            node = node.value
    if isinstance(node, ast.Name) and node.id == "self" \
            and last is not None:
        return last.attr
    return None


def _write_targets(n: ast.AST):
    if isinstance(n, ast.Assign):
        return n.targets
    if isinstance(n, (ast.AugAssign, ast.AnnAssign)):
        return [n.target]
    if isinstance(n, ast.Delete):
        return n.targets
    return []


_SCOPE_ROOTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _writes_under(roots) -> Set[str]:
    out: Set[str] = set()
    for root in roots:
        if isinstance(root, _SCOPE_ROOTS):
            continue
        for n in walk_no_scope(root):
            for t in _write_targets(n):
                if isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        a = self_base_attr(e)
                        if a:
                            out.add(a)
                else:
                    a = self_base_attr(t)
                    if a:
                        out.add(a)
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in MUTATORS:
                a = self_base_attr(n.func.value)
                if a:
                    out.add(a)
    return out


def stmt_self_writes(stmt: ast.AST) -> Set[str]:
    """``self`` attrs this block statement may modify at its own
    program point (head-only nodes contribute only their heads)."""
    return _writes_under(effective_roots(stmt))


def stmt_self_reads(stmt: ast.AST) -> Set[str]:
    """``self`` attrs loaded in this block statement's effective
    extent."""
    out: Set[str] = set()
    for root in effective_roots(stmt):
        for n in walk_no_scope(root):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.ctx, ast.Load) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                out.add(n.attr)
    return out


def stmt_self_calls(stmt: ast.AST) -> Set[str]:
    """Names of ``self.<m>(...)`` method calls in this statement's
    effective extent (one-hop expansion hook: the caller looks up what
    ``m`` writes)."""
    out: Set[str] = set()
    for root in effective_roots(stmt):
        for n in walk_no_scope(root):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == "self" \
                    and n.func.attr not in MUTATORS:
                out.add(n.func.attr)
    return out


def fn_self_writes(fn: ast.AST) -> Set[str]:
    """Every ``self`` attr the function may modify anywhere in its own
    scope (whole-body summary for one-hop call expansion)."""
    return _writes_under(ast.iter_child_nodes(fn))


def fn_self_accesses(fn: ast.AST) -> Set[str]:
    """Every ``self`` attr the function touches (read or write)."""
    out = fn_self_writes(fn)
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, _SCOPE_ROOTS):
            continue
        for n in walk_no_scope(child):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                out.add(n.attr)
    return out
