"""Per-node shared-memory object store (plasma-equivalent).

Role-equivalent to the reference's plasma store
(`src/ray/object_manager/plasma/store.cc:1`, `object_lifecycle_manager.h`,
`eviction_policy.h`, `plasma_allocator.h`): one store per node, hosted
*inside the raylet process* (as plasma runs inside the raylet —
`object_manager.cc:32`), holding sealed immutable objects in shared memory
with LRU eviction, pinning for primary copies, and disk fallback (spilling)
under memory pressure.

Two backends behind one interface:

- **native** (default): a C++ arena allocator (`native/arena_store.cpp`,
  bound via ctypes) — one mmap'd tmpfs file per node, first-fit free list
  with coalescing, C-side LRU eviction. Clients receive (arena path,
  offset, size) and map the arena once per process; create/get cost an
  allocator walk instead of per-object file syscalls. This is plasma's
  actual design (mmap'd arenas + dlmalloc + "FD passing" = sharing the
  arena mapping).
- **files**: one tmpfs file per object (pure-Python fallback when the
  native toolchain is unavailable; also selectable with
  ``RAY_TPU_object_store_backend=files``).

Clients (workers/drivers on the node) call create/seal/get via the raylet
RPC channel and then mmap directly — object bytes never cross the RPC.
"""

from __future__ import annotations

import asyncio
import mmap
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import GlobalConfig


class ObjectStoreFullError(Exception):
    pass


# Diagnostic: trace every client addref with a stack (flag read once —
# the env doesn't change mid-process and addref is on the get hot path).
_DEBUG_ADDREF = bool(os.environ.get("RTPU_DEBUG_ADDREF"))


@dataclass
class _Entry:
    object_id: bytes
    size: int
    path: str                    # arena path (native) or object file (files)
    offset: int = 0
    sealed: bool = False
    pinned: bool = False
    spilled_path: Optional[str] = None
    last_access: float = field(default_factory=time.monotonic)
    seal_event: asyncio.Event = field(default_factory=asyncio.Event)


class NodeObjectStore:
    """The node-side store state machine. All methods run on the raylet loop."""

    def __init__(self, capacity_bytes: int, shm_dir: str, spill_dir: str,
                 node_hex: str, backend: Optional[str] = None):
        self.capacity = capacity_bytes
        self.used = 0
        self._shm_dir = shm_dir
        self._spill_dir = spill_dir
        self._prefix = f"rtpu-{node_hex[:12]}-"
        self._entries: Dict[bytes, _Entry] = {}
        os.makedirs(spill_dir, exist_ok=True)
        self.num_evictions = 0
        self.num_spills = 0
        self.num_restores = 0
        self.spill_time_s = 0.0
        self.restore_time_s = 0.0

        backend = backend or getattr(GlobalConfig, "object_store_backend",
                                     "native")
        self._arena = None
        self._arena_map: Optional[mmap.mmap] = None
        if backend == "native":
            try:
                from ray_tpu._private.native_store import ArenaStore

                self._arena_path = os.path.join(
                    shm_dir, self._prefix + "arena")
                self._arena = ArenaStore(self._arena_path, capacity_bytes)
                f = open(self._arena_path, "r+b")
                self._arena_map = mmap.mmap(f.fileno(), capacity_bytes)
                self._arena_file = f
            except Exception:
                self._arena = None  # fall back to file-per-object
        self.backend = "native" if self._arena is not None else "files"

    # -- paths --------------------------------------------------------------
    def _path_for(self, object_id: bytes) -> str:
        return os.path.join(self._shm_dir, self._prefix + object_id.hex())

    # -- create / seal ------------------------------------------------------
    async def _with_full_retry(self, fn, attempts: int = 8,
                               delay_s: float = 0.15):
        """Client buffer releases land asynchronously: a store-full
        condition where every extent is reader-pinned usually clears
        within milliseconds once in-flight release RPCs arrive. One
        shared policy for every async entry point."""
        for i in range(attempts):
            try:
                return fn()
            except ObjectStoreFullError:
                if i == attempts - 1:
                    raise
                await asyncio.sleep(delay_s)

    async def create_async(self, object_id: bytes,
                           size: int) -> Tuple[str, int]:
        return await self._with_full_retry(
            lambda: self.create(object_id, size))

    async def put_bytes_async(self, object_id: bytes,
                              payload: bytes) -> None:
        return await self._with_full_retry(
            lambda: self.put_bytes(object_id, payload))

    def create(self, object_id: bytes, size: int) -> Tuple[str, int]:
        """Allocate space; returns (mmap path, offset-within-path)."""
        if object_id in self._entries:
            entry = self._entries[object_id]
            if entry.spilled_path is not None:
                # The previous copy's arena extent was freed by the spill —
                # its recorded offset is stale. Restore first so the caller
                # gets a live extent, never memory owned by another object.
                self._restore(entry)
            if entry.sealed or entry.size == size:
                return entry.path, entry.offset  # idempotent re-create
            raise ValueError("object already being created with different size")
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity {self.capacity}")
        if self._arena is not None:
            offset = self._arena_create(object_id, size)
            entry = _Entry(object_id, size, self._arena_path, offset)
        else:
            self._ensure_space(size)
            path = self._path_for(object_id)
            with open(path, "wb") as f:
                f.truncate(size)
            entry = _Entry(object_id, size, path)
            self.used += size
        self._entries[object_id] = entry
        return entry.path, entry.offset

    def _arena_create(self, object_id: bytes, size: int) -> int:
        offset = self._arena.create(object_id, size)
        if offset is None:
            # 1) LRU-evict unpinned sealed copies (C side picks victims).
            for evicted in self._arena.evict_for(size):
                e = self._entries.pop(evicted, None)
                if e is not None and e.spilled_path is None:
                    self.num_evictions += 1
            offset = self._arena.create(object_id, size)
        while offset is None:
            # 2) Spill pinned primaries (LRU first) to disk.
            victim = self._arena.lru_pinned()
            if victim is None:
                detail = ", ".join(
                    f"{oid.hex()[:6]}(py sealed={e.sealed} "
                    f"pinned={e.pinned} "
                    f"spilled={e.spilled_path is not None} "
                    f"C={self._arena.entry_flags(oid)})"
                    for oid, e in list(self._entries.items())[:16])
                raise ObjectStoreFullError(
                    f"need {size} bytes; arena exhausted and nothing "
                    f"spillable [{detail}]")
            self._spill_arena(victim)
            offset = self._arena.create(object_id, size)
        self.used = self._arena.stats()[1]
        return offset

    def seal(self, object_id: bytes) -> None:
        entry = self._entries.get(object_id)
        if entry is None:
            raise KeyError(f"seal of unknown object {object_id.hex()}")
        if self._arena is not None and entry.spilled_path is None:
            self._arena.seal(object_id)
        entry.sealed = True
        entry.last_access = time.monotonic()
        entry.seal_event.set()

    def put_bytes(self, object_id: bytes, payload: bytes) -> None:
        """Create+write+seal in one step (used by the pull path)."""
        if self.contains(object_id):
            return
        path, offset = self.create(object_id, len(payload))
        if self._arena is not None:
            _populate(self._arena_map, offset, len(payload),
                      _MADV_POPULATE_WRITE)
            self._arena_map[offset:offset + len(payload)] = payload
        else:
            with open(path, "r+b") as f:
                f.write(payload)
        self.seal(object_id)

    # -- read ---------------------------------------------------------------
    def contains(self, object_id: bytes) -> bool:
        e = self._entries.get(object_id)
        return e is not None and e.sealed and e.spilled_path is None

    async def get(self, object_id: bytes, timeout: Optional[float]
                  ) -> Optional[Tuple[str, int, int]]:
        """Wait for a local sealed copy; returns (path, size, offset)."""
        entry = self._entries.get(object_id)
        if entry is None:
            if timeout is None or timeout <= 0:
                return None
            deadline = time.monotonic() + timeout
            while entry is None and time.monotonic() < deadline:
                await asyncio.sleep(0.005)
                entry = self._entries.get(object_id)
            if entry is None:
                return None
        if not entry.sealed:
            try:
                await asyncio.wait_for(
                    entry.seal_event.wait(),
                    None if timeout is None else max(timeout, 0.001),
                )
            except asyncio.TimeoutError:
                return None
        if entry.spilled_path is not None:
            # Re-check per attempt: a concurrent getter may restore this
            # entry while we sleep (spilled_path goes None and the spill
            # file is gone — calling _restore again would crash).
            await self._with_full_retry(
                lambda: (self._restore(entry)
                         if entry.spilled_path is not None else None))
        entry.last_access = time.monotonic()
        if self._arena is not None:
            # refresh C-side LRU stamp
            self._arena.get(object_id)
        return entry.path, entry.size, entry.offset

    def write_into(self, object_id: bytes, offset: int, data: bytes) -> None:
        """Server-side write (pull path): into the unsealed object."""
        entry = self._entries[object_id]
        if self._arena is not None:
            base = entry.offset + offset
            self._arena_map[base:base + len(data)] = data
        else:
            with open(entry.path, "r+b") as f:
                f.seek(offset)
                f.write(data)

    def read_bytes(self, object_id: bytes, offset: int, length: int) -> bytes:
        """Server-side read for serving remote pulls (chunked)."""
        entry = self._entries[object_id]
        if entry.spilled_path is not None:
            self._restore(entry)
        if self._arena is not None:
            base = entry.offset + offset
            return bytes(self._arena_map[base:base + length])
        with open(entry.path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def size_of(self, object_id: bytes) -> int:
        return self._entries[object_id].size

    # -- client mapping refs (arena only; per-object files survive unlink
    #    under an existing mmap, so the files backend needs none) ----------
    def addref_client(self, object_id: bytes) -> None:
        if self._arena is not None and object_id in self._entries:
            if _DEBUG_ADDREF:
                import sys
                import traceback
                sys.stderr.write(f"ADDREF {object_id.hex()[:6]}\n"
                                 + "".join(traceback.format_stack()[-4:]))
            self._arena.addref(object_id, 1)

    def release_client(self, object_id: bytes) -> None:
        if self._arena is not None and object_id in self._entries:
            self._arena.addref(object_id, -1)

    # -- pin / delete -------------------------------------------------------
    def pin(self, object_id: bytes) -> None:
        e = self._entries.get(object_id)
        if e is not None:
            e.pinned = True
            if self._arena is not None and e.spilled_path is None:
                self._arena.pin(object_id, True)

    def unpin(self, object_id: bytes) -> None:
        e = self._entries.get(object_id)
        if e is not None:
            e.pinned = False
            if self._arena is not None and e.spilled_path is None:
                self._arena.pin(object_id, False)

    def delete(self, object_ids: List[bytes]) -> None:
        for oid in object_ids:
            entry = self._entries.pop(oid, None)
            if entry is None:
                continue
            if self._arena is not None:
                if entry.spilled_path is None:
                    self._arena.delete(oid)
                    self.used = self._arena.stats()[1]
                else:
                    try:
                        os.unlink(entry.spilled_path)
                    except FileNotFoundError:
                        pass
                continue
            self.used -= entry.size if entry.spilled_path is None else 0
            for p in (entry.path, entry.spilled_path):
                if p is not None and p != getattr(self, "_arena_path", None):
                    try:
                        os.unlink(p)
                    except FileNotFoundError:
                        pass

    # -- eviction / spilling (files backend + spill common path) ------------
    def _ensure_space(self, needed: int) -> None:
        if self.used + needed <= self.capacity:
            return
        candidates = sorted(
            (e for e in self._entries.values()
             if e.sealed and e.spilled_path is None),
            key=lambda e: e.last_access,
        )
        for entry in candidates:
            if self.used + needed <= self.capacity:
                break
            if entry.pinned:
                self._spill(entry)
            else:
                self.used -= entry.size
                self.num_evictions += 1
                try:
                    os.unlink(entry.path)
                except FileNotFoundError:
                    pass
                del self._entries[entry.object_id]
        if self.used + needed > self.capacity:
            raise ObjectStoreFullError(
                f"need {needed} bytes but only "
                f"{self.capacity - self.used} available after eviction")

    def _spill_target(self, object_id: bytes) -> str:
        return os.path.join(self._spill_dir,
                            self._prefix + object_id.hex())

    def _spill(self, entry: _Entry) -> None:
        t0 = time.perf_counter()
        dest = self._spill_target(entry.object_id)
        shutil.move(entry.path, dest)
        entry.spilled_path = dest
        self.used -= entry.size
        self.num_spills += 1
        self.spill_time_s += time.perf_counter() - t0

    def _spill_arena(self, victim: Tuple[bytes, int, int]) -> None:
        t0 = time.perf_counter()
        oid, offset, size = victim
        dest = self._spill_target(oid)
        with open(dest, "wb") as f:
            f.write(self._arena_map[offset:offset + size])
        self._arena.delete(oid)
        entry = self._entries.get(oid)
        if entry is not None:
            entry.spilled_path = dest
        self.num_spills += 1
        self.spill_time_s += time.perf_counter() - t0

    def _restore(self, entry: _Entry) -> None:
        t0 = time.perf_counter()
        if self._arena is not None:
            offset = self._arena_create(entry.object_id, entry.size)
            with open(entry.spilled_path, "rb") as f:
                self._arena_map[offset:offset + entry.size] = f.read()
            os.unlink(entry.spilled_path)
            entry.spilled_path = None
            entry.offset = offset
            self._arena.seal(entry.object_id)
            if entry.pinned:
                self._arena.pin(entry.object_id, True)
        else:
            self._ensure_space(entry.size)
            shutil.move(entry.spilled_path, entry.path)
            entry.spilled_path = None
            self.used += entry.size
        self.num_restores += 1
        self.restore_time_s += time.perf_counter() - t0

    # -- stats --------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        if self._arena is not None:
            cap, used, _n, evictions = self._arena.stats()
            self.used = used
            self.num_evictions = max(self.num_evictions, evictions)
        pinned_bytes = 0
        spilled_bytes = 0
        for e in self._entries.values():
            if e.spilled_path is not None:
                spilled_bytes += e.size
            elif e.pinned:
                pinned_bytes += e.size
        return {
            "backend": self.backend,
            "capacity": self.capacity,
            "used": self.used,
            "num_objects": len(self._entries),
            "pinned_bytes": pinned_bytes,
            "spilled_bytes": spilled_bytes,
            "num_evictions": self.num_evictions,
            "num_spills": self.num_spills,
            "num_restores": self.num_restores,
            "spill_time_s": self.spill_time_s,
            "restore_time_s": self.restore_time_s,
        }

    def object_table(self, limit: Optional[int] = None
                     ) -> List[Dict[str, Any]]:
        """Per-object rows for memory introspection (``memory_summary``,
        ``GET /api/memory``), largest first."""
        now = time.monotonic()
        rows = [{
            "object_id": e.object_id.hex(),
            "size": e.size,
            "sealed": e.sealed,
            "pinned": e.pinned,
            "spilled": e.spilled_path is not None,
            "idle_s": max(now - e.last_access, 0.0),
        } for e in self._entries.values()]
        rows.sort(key=lambda r: r["size"], reverse=True)
        return rows[:limit] if limit else rows

    def cleanup(self) -> None:
        self.delete(list(self._entries.keys()))
        if self._arena is not None:
            try:
                self._arena_map.close()
                self._arena_file.close()
                self._arena.close()
                os.unlink(self._arena_path)
            except Exception:
                pass
            self._arena = None


# ---------------------------------------------------------------------------
# Client-side zero-copy views
# ---------------------------------------------------------------------------

# One shared read-write mapping per arena path per client process — this is
# plasma's "FD passing": every client shares the same physical pages.
_client_arenas: Dict[str, mmap.mmap] = {}
_client_arena_files: Dict[str, Any] = {}

_MADV_POPULATE_READ = 22   # Linux; absent from the mmap module's constants
_MADV_POPULATE_WRITE = 23
_POPULATE_MIN = 64 * 1024


def _populate(arena: mmap.mmap, offset: int, size: int, advice: int) -> None:
    """Fault an extent's pages into THIS process in one syscall: the store
    pre-commits tmpfs pages server-side, but each client mapping still pays
    a minor fault per page on first touch — ~5ms per 10 MiB if taken one by
    one inside memcpy, ~0.2ms batched here."""
    if size < _POPULATE_MIN:
        return
    start = offset & ~4095
    try:
        arena.madvise(advice, start, offset + size - start)
    except (OSError, ValueError):
        pass


def _client_arena_map(path: str) -> mmap.mmap:
    m = _client_arenas.get(path)
    if m is None:
        f = open(path, "r+b")
        m = mmap.mmap(f.fileno(), os.path.getsize(path))
        _client_arenas[path] = m
        _client_arena_files[path] = f
    return m


class MappedObject:
    """A client-side zero-copy view of a sealed store object.

    Plasma client-buffer semantics: the mapping holds a store-side
    client ref (the raylet will not spill/evict the extent under a live
    reader); when the last deserialized value sharing the buffer dies,
    ``close`` runs once and fires ``on_release`` so the worker tells the
    raylet to drop that ref. Without this, every restored object stayed
    reader-pinned forever and a small arena wedged with 'nothing
    spillable'."""

    __slots__ = ("_file", "_mmap", "_shared", "view", "on_release",
                 "_released", "__weakref__")

    def __init__(self, path: str, size: int, offset: int = 0,
                 on_release=None):
        self.on_release = on_release
        self._released = False
        if offset or os.path.basename(path).endswith("arena"):
            self._shared = True
            self._file = None
            self._mmap = None
            arena = _client_arena_map(path)
            _populate(arena, offset, size, _MADV_POPULATE_READ)
            self.view = memoryview(arena)[offset:offset + size]
            return
        self._shared = False
        self._file = open(path, "rb")
        if size > 0:
            self._mmap = mmap.mmap(self._file.fileno(), size,
                                   prot=mmap.PROT_READ)
            self.view = memoryview(self._mmap)
        else:
            self._mmap = None
            self.view = memoryview(b"")

    def close(self):
        try:
            self.view.release()
            if self._mmap is not None:
                self._mmap.close()
            if self._file is not None:
                self._file.close()
        except (BufferError, ValueError, OSError):
            pass
        cb, self.on_release = self.on_release, None
        if cb is not None and not self._released:
            self._released = True
            try:
                cb()
            except Exception:
                pass

    def mark_released(self) -> None:
        """The client ref is already being dropped elsewhere (bulk
        release at shutdown): suppress the per-object callback."""
        self._released = True
        self.on_release = None

    def __del__(self):
        self.close()


class WritableObject:
    """A client-side writable mapping used between create() and seal()."""

    __slots__ = ("_file", "_mmap", "_shared", "view")

    def __init__(self, path: str, size: int, offset: int = 0):
        if offset or os.path.basename(path).endswith("arena"):
            self._shared = True
            self._file = None
            self._mmap = None
            arena = _client_arena_map(path)
            _populate(arena, offset, size, _MADV_POPULATE_WRITE)
            self.view = memoryview(arena)[offset:offset + size]
            return
        self._shared = False
        self._file = open(path, "r+b")
        self._mmap = mmap.mmap(self._file.fileno(), size)
        self.view = memoryview(self._mmap)

    def close(self):
        try:
            self.view.release()
            if self._mmap is not None:
                self._mmap.close()
            if self._file is not None:
                self._file.close()
        except (BufferError, ValueError, OSError):
            pass
