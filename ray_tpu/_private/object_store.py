"""Per-node shared-memory object store (plasma-equivalent).

Role-equivalent to the reference's plasma store
(`src/ray/object_manager/plasma/store.cc:1`, `object_lifecycle_manager.h`,
`eviction_policy.h`): one store per node, hosted *inside the raylet process*
(as plasma runs inside the raylet — `object_manager.cc:32`), holding sealed
immutable objects in shared memory with LRU eviction, pinning for primary
copies, and disk fallback (spilling) when memory pressure demands.

Implementation: each object is a file in ``/dev/shm`` (tmpfs) mmap'd by
clients — the moral equivalent of plasma's mmap'd arenas with FD passing; the
"FD pass" is opening the same tmpfs path, which yields the same zero-copy
shared pages. A C++ arena allocator can replace the per-object-file scheme
behind this same interface (see native/).

Clients (workers/drivers on the node) call create/seal/get via the raylet RPC
channel and then mmap the returned path directly — data never crosses the RPC.
"""

from __future__ import annotations

import asyncio
import mmap
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


class ObjectStoreFullError(Exception):
    pass


@dataclass
class _Entry:
    object_id: bytes
    size: int
    path: str
    sealed: bool = False
    pinned: bool = False
    spilled_path: Optional[str] = None
    last_access: float = field(default_factory=time.monotonic)
    seal_event: asyncio.Event = field(default_factory=asyncio.Event)


class NodeObjectStore:
    """The node-side store state machine. All methods run on the raylet loop."""

    def __init__(self, capacity_bytes: int, shm_dir: str, spill_dir: str,
                 node_hex: str):
        self.capacity = capacity_bytes
        self.used = 0
        self._shm_dir = shm_dir
        self._spill_dir = spill_dir
        self._prefix = f"rtpu-{node_hex[:12]}-"
        self._entries: Dict[bytes, _Entry] = {}
        os.makedirs(spill_dir, exist_ok=True)
        self.num_evictions = 0
        self.num_spills = 0
        self.num_restores = 0

    # -- paths --------------------------------------------------------------
    def _path_for(self, object_id: bytes) -> str:
        return os.path.join(self._shm_dir, self._prefix + object_id.hex())

    # -- create / seal ------------------------------------------------------
    def create(self, object_id: bytes, size: int) -> str:
        if object_id in self._entries:
            entry = self._entries[object_id]
            if entry.sealed or entry.size == size:
                return entry.path  # idempotent re-create
            raise ValueError("object already being created with different size")
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity {self.capacity}")
        self._ensure_space(size)
        path = self._path_for(object_id)
        with open(path, "wb") as f:
            f.truncate(size)
        self._entries[object_id] = _Entry(object_id, size, path)
        self.used += size
        return path

    def seal(self, object_id: bytes) -> None:
        entry = self._entries.get(object_id)
        if entry is None:
            raise KeyError(f"seal of unknown object {object_id.hex()}")
        entry.sealed = True
        entry.last_access = time.monotonic()
        entry.seal_event.set()

    def put_bytes(self, object_id: bytes, payload: bytes) -> None:
        """Create+write+seal in one step (used by the pull path)."""
        if self.contains(object_id):
            return
        path = self.create(object_id, len(payload))
        with open(path, "r+b") as f:
            f.write(payload)
        self.seal(object_id)

    # -- read ---------------------------------------------------------------
    def contains(self, object_id: bytes) -> bool:
        e = self._entries.get(object_id)
        return e is not None and e.sealed and e.spilled_path is None

    async def get(self, object_id: bytes, timeout: Optional[float]
                  ) -> Optional[Tuple[str, int]]:
        """Wait for a local sealed copy; returns (path, size) or None."""
        entry = self._entries.get(object_id)
        if entry is None:
            if timeout is None or timeout <= 0:
                return None
            deadline = time.monotonic() + timeout
            while entry is None and time.monotonic() < deadline:
                await asyncio.sleep(0.005)
                entry = self._entries.get(object_id)
            if entry is None:
                return None
        if not entry.sealed:
            try:
                await asyncio.wait_for(
                    entry.seal_event.wait(),
                    None if timeout is None else max(timeout, 0.001),
                )
            except asyncio.TimeoutError:
                return None
        if entry.spilled_path is not None:
            self._restore(entry)
        entry.last_access = time.monotonic()
        return entry.path, entry.size

    def read_bytes(self, object_id: bytes, offset: int, length: int) -> bytes:
        """Server-side read for serving remote pulls (chunked)."""
        entry = self._entries[object_id]
        if entry.spilled_path is not None:
            self._restore(entry)
        with open(entry.path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def size_of(self, object_id: bytes) -> int:
        return self._entries[object_id].size

    # -- pin / delete -------------------------------------------------------
    def pin(self, object_id: bytes) -> None:
        e = self._entries.get(object_id)
        if e is not None:
            e.pinned = True

    def unpin(self, object_id: bytes) -> None:
        e = self._entries.get(object_id)
        if e is not None:
            e.pinned = False

    def delete(self, object_ids: List[bytes]) -> None:
        for oid in object_ids:
            entry = self._entries.pop(oid, None)
            if entry is None:
                continue
            self.used -= entry.size if entry.spilled_path is None else 0
            for p in (entry.path, entry.spilled_path):
                if p is not None:
                    try:
                        os.unlink(p)
                    except FileNotFoundError:
                        pass

    # -- eviction / spilling ------------------------------------------------
    def _ensure_space(self, needed: int) -> None:
        if self.used + needed <= self.capacity:
            return
        # Evict or spill LRU sealed objects until there is room.
        candidates = sorted(
            (e for e in self._entries.values()
             if e.sealed and e.spilled_path is None),
            key=lambda e: e.last_access,
        )
        for entry in candidates:
            if self.used + needed <= self.capacity:
                break
            if entry.pinned:
                self._spill(entry)
            else:
                # Secondary/unpinned copy: safe to drop entirely.
                self.used -= entry.size
                self.num_evictions += 1
                try:
                    os.unlink(entry.path)
                except FileNotFoundError:
                    pass
                del self._entries[entry.object_id]
        if self.used + needed > self.capacity:
            raise ObjectStoreFullError(
                f"need {needed} bytes but only "
                f"{self.capacity - self.used} available after eviction")

    def _spill(self, entry: _Entry) -> None:
        dest = os.path.join(self._spill_dir, os.path.basename(entry.path))
        shutil.move(entry.path, dest)
        entry.spilled_path = dest
        self.used -= entry.size
        self.num_spills += 1

    def _restore(self, entry: _Entry) -> None:
        self._ensure_space(entry.size)
        shutil.move(entry.spilled_path, entry.path)
        entry.spilled_path = None
        self.used += entry.size
        self.num_restores += 1

    # -- stats --------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "num_objects": len(self._entries),
            "num_evictions": self.num_evictions,
            "num_spills": self.num_spills,
            "num_restores": self.num_restores,
        }

    def cleanup(self) -> None:
        self.delete(list(self._entries.keys()))


class MappedObject:
    """A client-side zero-copy view of a sealed store object."""

    __slots__ = ("_file", "_mmap", "view")

    def __init__(self, path: str, size: int):
        self._file = open(path, "rb")
        if size > 0:
            self._mmap = mmap.mmap(self._file.fileno(), size,
                                   prot=mmap.PROT_READ)
            self.view = memoryview(self._mmap)
        else:
            self._mmap = None
            self.view = memoryview(b"")

    def close(self):
        try:
            self.view.release()
            if self._mmap is not None:
                self._mmap.close()
            self._file.close()
        except (BufferError, ValueError, OSError):
            pass


class WritableObject:
    """A client-side writable mapping used between create() and seal()."""

    __slots__ = ("_file", "_mmap", "view")

    def __init__(self, path: str, size: int):
        self._file = open(path, "r+b")
        self._mmap = mmap.mmap(self._file.fileno(), size)
        self.view = memoryview(self._mmap)

    def close(self):
        try:
            self.view.release()
            self._mmap.close()
            self._file.close()
        except (BufferError, ValueError, OSError):
            pass
