"""Raylet — the per-node daemon.

Role-equivalent to the reference's `src/ray/raylet/` NodeManager: hosts the
node's shared-memory object store (as plasma runs inside the raylet —
`object_manager.cc:32`), manages the warm worker pool
(`worker_pool.h:104` PopWorker), serves the worker-lease protocol with
hybrid-policy spillback (`node_manager.cc:1714` HandleRequestWorkerLease,
`cluster_task_manager.h:70`), performs placement-group bundle 2-phase-commit
(`placement_group_resource_manager.h:54-61`), transfers objects node-to-node
in chunks (`pull_manager.h:52`), and assigns TPU chip instances to leases so
workers can set `TPU_VISIBLE_CHIPS` (reference: `accelerators/tpu.py:158`).
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import subprocess
import sys
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.object_store import NodeObjectStore
from ray_tpu._private.resources import (
    CPU, MEM, OBJECT_STORE_MEM, TPU, NodeResources, ResourceSet,
)
from ray_tpu._private.rpc import RpcClient, RpcServer, get_io_loop, spawn_task
from ray_tpu._private.scheduling_policy import (
    ClusterView, is_feasible_anywhere, pick_node,
)


class _WorkerHandle:
    def __init__(self, worker_id: bytes, proc: subprocess.Popen,
                 addr: Tuple[str, int], job_id: bytes,
                 pool_key: Optional[bytes] = None,
                 runtime_env: Optional[Dict[str, Any]] = None):
        self.worker_id = worker_id
        self.proc = proc
        self.addr = addr
        self.job_id = job_id
        # Pool identity: (job, runtime-env hash) — reference worker_pool
        # keys cached workers the same way so a task never runs in another
        # env's worker.
        self.pool_key = pool_key if pool_key is not None else job_id
        self.runtime_env = runtime_env
        self.env_uris: list = []      # runtime_env cache entries in use
        self.out_path: Optional[str] = None   # stdout log file
        self.err_path: Optional[str] = None   # stderr log file
        self.lease: Optional[Dict[str, Any]] = None  # demand + tpu ids
        self.is_actor = False
        self.actor_id: Optional[bytes] = None
        # Bumped on every grant (task lease OR dedicated-actor lease).
        # return_worker must echo it back: a return processed late — a
        # slow raylet can apply a frame a minute after it was sent —
        # must not be able to strip a lease the worker acquired SINCE
        # (observed: a stale task-lease return re-offered a worker that
        # had become a dedicated ACTOR worker, and the next task-lease
        # failure path SIGKILLed the actor).
        self.lease_epoch = 0
        self.last_idle = time.monotonic()
        # Set when the worker registers (or dies before registering) —
        # the spawn throttle waits on this instead of polling.
        self.registered = asyncio.Event()


class Raylet:
    def __init__(self, node_id: bytes, host: str, gcs_addr: Tuple[str, int],
                 resources: Dict[str, float], labels: Dict[str, str],
                 session_dir: str, object_store_capacity: int,
                 port: int = 0):
        self.node_id = node_id
        self.host = host
        self.session_dir = session_dir
        self.gcs = RpcClient(*gcs_addr)
        self.gcs_addr = gcs_addr

        self.server = RpcServer(host, port)
        self._register_handlers()

        # --- resources ---
        self.labels = labels
        self.total = ResourceSet(resources)
        self.local = NodeResources(self.total, labels)
        # TPU chip instance pool for TPU_VISIBLE_CHIPS assignment.
        n_tpu = int(resources.get(TPU, 0))
        self._free_tpu_chips: List[int] = list(range(n_tpu))
        # Chip dedicated to fractional (<1 chip) leases; refcounted so it is
        # never co-assigned to a whole-chip lease.
        self._frac_chip: Optional[int] = None
        self._frac_users = 0

        # --- cluster view (replicated from GCS heartbeats) ---
        self.view = ClusterView()
        self._node_addrs: Dict[bytes, Tuple[str, int]] = {}

        # --- object store ---
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else session_dir
        self.store = NodeObjectStore(
            object_store_capacity, shm_dir,
            os.path.join(session_dir, "spill", node_id.hex()[:12]),
            node_id.hex())

        # --- worker pool ---
        self.workers: Dict[bytes, _WorkerHandle] = {}
        # Keyed by pool_key = job_id (+ runtime-env hash when set).
        self._idle: Dict[bytes, deque] = defaultdict(deque)
        self._starting: Dict[bytes, int] = defaultdict(int)
        self._pending_pop: Dict[bytes, deque] = defaultdict(deque)
        self._max_workers = (GlobalConfig.max_workers_per_node
                             or max(int(resources.get(CPU, 1)), 1) * 4)

        # --- queued lease requests waiting for local resources ---
        self._lease_queue: deque = deque()
        self._lease_queue_event = asyncio.Event()
        # Demands recently rejected as infeasible-anywhere: the autoscaler's
        # scale-up signal (owners retry from their side, so these never sit
        # in _lease_queue). Deduped by shape — lease retries of one task
        # must not read as N distinct demands.
        self._unfulfilled: Dict[tuple, float] = {}

        # --- placement group bundles ---
        # (pg_id, idx) -> {"resources": ResourceSet, "committed": bool}
        self._bundles: Dict[Tuple[bytes, int], Dict[str, Any]] = {}

        self._remote_raylets: Dict[Tuple[str, int], RpcClient] = {}
        # client (worker_id) -> oids it holds arena mappings of; released
        # in bulk when the client process dies (plasma: per-client object
        # refs cleared on disconnect).
        self._client_mapped: Dict[bytes, Set[bytes]] = defaultdict(set)
        self._dead = False
        self._oom_kills = 0
        # worker_id -> True for workers the memory monitor shot; owners ask
        # via get_worker_exit_info to turn the crash into OutOfMemoryError.
        self._oom_killed: Set[bytes] = set()
        # Workers preemptively rescheduled by the memory monitor BELOW
        # the kill threshold: classified PREEMPT_RESCHEDULE (retriable —
        # the owner's normal crash-retry path reruns the task), never
        # OOM_KILLED, so the user sees a reschedule, not an error.
        self._preempts = 0
        self._preempted: Set[bytes] = set()
        self._last_preempt_ts = 0.0
        # Workers whose death THIS raylet caused on purpose (pool cap,
        # idle TTL, lease return, kill_worker, graceful worker_exiting):
        # the reaper classifies them INTENDED_EXIT instead of reading the
        # SIGKILL we sent as SYSTEM_ERROR.
        self._intended_exit: Set[bytes] = set()
        # worker_id -> exit forensics (taxonomy, exit code, last log
        # lines) captured at reap time; served via get_worker_exit_info
        # so owners enrich WorkerCrashedError/ActorDiedError messages.
        self._exit_info: Dict[bytes, Dict[str, Any]] = {}
        # Spill counter watermark for SPILL_PRESSURE events.
        self._spills_reported = 0
        self._worker_info_cache: Dict[bytes, Any] = {}
        # pool_key -> (message, ts) of the last runtime_env setup failure:
        # turned into a fast lease error so owners fail tasks with
        # RuntimeEnvSetupError instead of hot-looping spawn attempts.
        self._env_failures: Dict[bytes, Tuple[str, float]] = {}
        # worker_id -> RpcClient used by the memory monitor's busy probe.
        self._worker_probe_clients: Dict[bytes, Any] = {}
        # Killed/retired worker Popen handles awaiting reap (zombies
        # otherwise; see _retire_proc).
        self._dying: List[subprocess.Popen] = []

    # ------------------------------------------------------------------- boot
    def start(self) -> int:
        port = self.server.start()
        reply = self.gcs.call(
            "register_node", node_id=self.node_id,
            addr=(self.host, port),
            resources=self.total.to_dict(), labels=self.labels,
            object_store_capacity=self.store.capacity)
        GlobalConfig.load_system_config(reply["system_config"])
        self._apply_nodes_snapshot(reply["nodes"])
        io = get_io_loop()
        io.submit(self._heartbeat_loop())
        io.submit(self._reaper_loop())
        io.submit(self._lease_dispatch_loop())
        io.submit(self._log_monitor_loop())
        io.submit(self._memory_monitor_loop())
        io.submit(self._reporter_loop())
        io.submit(self._stall_watchdog())
        return port

    async def _stall_watchdog(self):
        """Log when this raylet's event loop stops turning (reference:
        instrumented_io_context's lag stats). A stalled loop silently
        breaks heartbeats, worker pings, and lease handling — the log
        line turns 'mystery mass worker death' into a diagnosis."""
        last = time.monotonic()
        while not self._dead:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            gap = now - last - 1.0
            if gap > 5.0:
                sys.stderr.write(
                    f"[raylet {self.node_id.hex()[:8]}] event loop "
                    f"stalled {gap:.1f}s (workers={len(self.workers)})\n")
                sys.stderr.flush()
            last = now

    def _register_handlers(self):
        s = self.server
        for name in [
            "request_worker_lease", "return_worker", "lease_worker_for_actor",
            "register_worker", "worker_exiting",
            "create_object", "seal_object", "put_object", "get_object",
            "contains_object",
            "delete_objects", "pin_object", "unpin_object", "read_chunk",
            "release_object", "release_objects",
            "object_info", "store_stats", "memory_stats",
            "prepare_bundle", "commit_bundle", "return_bundle",
            "kill_worker", "node_stats", "shutdown_node", "get_tasks_info",
            "profile_worker", "dump_stacks",
            "get_worker_exit_info", "runtime_env_stats", "get_log",
        ]:
            s.register(name, getattr(self, f"_h_{name}"))

    def _report_event(self, event_type: str, message: str,
                      severity: Optional[str] = None, **extra) -> None:
        """Fire-and-forget a typed event to the GCS ClusterEventLog."""
        if self._dead:
            return

        async def _send():
            try:
                await self.gcs.acall(
                    "report_cluster_event", event_type=event_type,
                    message=message, severity=severity,
                    node_id=self.node_id.hex(), extra=extra, timeout=10)
            except Exception:
                pass

        spawn_task(_send())

    # -------------------------------------------------------------- heartbeat
    async def _heartbeat_loop(self):
        from ray_tpu._private.rpc import debug_log

        _dbg = debug_log("hb")
        # Resource reports drive spillback freshness, so they run much
        # faster than liveness needs (reference splits these the same way:
        # report_resources_period vs health check period).
        period = GlobalConfig.raylet_report_resources_period_ms / 1000
        have_seq = 0
        while not self._dead:
            try:
                now = time.monotonic()
                _dbg("send")
                pending = [item[0].to_dict()
                           for item in list(self._lease_queue)[:64]]
                for key, ts in list(self._unfulfilled.items()):
                    if now - ts >= 10.0:
                        del self._unfulfilled[key]
                    else:
                        pending.append(dict(key))
                reply = await self.gcs.acall(
                    "heartbeat", node_id=self.node_id,
                    available=self.local.available.to_dict(),
                    total=self.local.total.to_dict(),
                    pending_demands=pending,
                    num_workers=len(self.workers),
                    have_seq=have_seq,
                    timeout=10)
                _dbg("reply ok")
                if reply.get("unknown"):
                    # The GCS doesn't know us: it restarted (bounce) —
                    # re-register with our existing identity and keep all
                    # local state; leases/workers/objects are untouched
                    # (reference: NotifyGCSRestart -> re-register,
                    # node_manager.proto:366).
                    _dbg("gcs bounce detected; re-registering")
                    rereg = await self.gcs.acall(
                        "register_node", node_id=self.node_id,
                        addr=(self.host, self.server.port),
                        resources=self.local.total.to_dict(),
                        labels=self.labels,
                        object_store_capacity=self.store.capacity,
                        timeout=10)
                    if "nodes" in rereg:
                        self._apply_nodes_snapshot(rereg["nodes"])
                        have_seq = 0  # fresh GCS numbers from 1 again
                elif "nodes" in reply:
                    self._apply_nodes_snapshot(reply["nodes"])
                    have_seq = reply.get("seq", 0)
            except Exception as e:
                _dbg("EXC", repr(e))
            await asyncio.sleep(period)

    def _apply_nodes_snapshot(self, nodes):
        seen = set()
        for n in nodes:
            if n["state"] != "ALIVE":
                self.view.remove_node(n["node_id"])
                continue
            seen.add(n["node_id"])
            self._node_addrs[n["node_id"]] = tuple(n["addr"])
            if n["node_id"] == self.node_id:
                # Authoritative local view is self.local; skip.
                self.view.update_node(n["node_id"], self.local)
                continue
            nr = NodeResources(ResourceSet(n["total"]), n["labels"])
            nr.available = ResourceSet(n["available"])
            self.view.update_node(n["node_id"], nr)
        for node_id in list(self.view.nodes.keys()):
            if node_id not in seen and node_id != self.node_id:
                self.view.remove_node(node_id)

    # ------------------------------------------------------------ worker pool
    def _worker_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        # The node's routable address: workers bind/advertise their RPC
        # servers on it (not loopback) so cross-host owner RPCs, object
        # pulls, and jax.distributed rendezvous work on real clusters.
        env["RAY_TPU_NODE_IP"] = self.host
        # Accelerator hygiene (reference: ray sets CUDA_VISIBLE_DEVICES=""
        # for non-GPU workers): on a node with NO TPU resource, workers
        # must never engage a real accelerator backend — site hooks keyed
        # on this env var initialize the TPU transport inside EVERY
        # python process, and a down/contended transport then hangs any
        # worker whose code merely asks jax for a device count (observed:
        # a train worker wedged in make_c_api_client for 180 s inside the
        # test suite). Opt out with RAY_TPU_KEEP_ACCEL_HOOK=1.
        if (not self.total.get("TPU")
                and not env.get("RAY_TPU_KEEP_ACCEL_HOOK")):
            env.pop("PALLAS_AXON_POOL_IPS", None)
        return env

    def _runtime_env_manager(self):
        if getattr(self, "_renv_manager", None) is None:
            from ray_tpu.runtime_env.manager import RuntimeEnvManager

            self._renv_manager = RuntimeEnvManager(
                os.path.join(self.session_dir, "runtime_envs"), self.gcs)
        return self._renv_manager

    def _release_worker_env(self, handle) -> None:
        """Per-worker teardown at every removal site: runtime_env cache
        refs plus the memory monitor's probe client."""
        if handle is not None:
            client = self._worker_probe_clients.pop(handle.worker_id, None)
            if client is not None:
                try:
                    client.close()
                except Exception:
                    pass
        if handle is not None and handle.env_uris:
            uris, handle.env_uris = handle.env_uris, []
            try:
                self._runtime_env_manager().release(uris)
            except Exception:
                pass

    async def _h_runtime_env_stats(self):
        return self._runtime_env_manager().stats()

    @staticmethod
    def _pool_key(job_id: bytes, runtime_env: Optional[Dict[str, Any]]
                  ) -> bytes:
        if not runtime_env:
            return job_id
        digest = hashlib.md5(json.dumps(
            runtime_env, sort_keys=True, default=str).encode()).digest()
        return job_id + digest[:8]

    def _spawn_worker(self, job_id: bytes,
                      runtime_env: Optional[Dict[str, Any]] = None) -> None:
        pool_key = self._pool_key(job_id, runtime_env)
        self._starting[pool_key] += 1
        spawn_task(
            self._spawn_worker_async(job_id, runtime_env, pool_key))

    async def _spawn_worker_async(self, job_id: bytes,
                                  runtime_env: Optional[Dict[str, Any]],
                                  pool_key: bytes) -> None:
        """Fork/exec OFF the event loop: Popen of this jax-preloaded
        process takes ~100ms+, and a replenish burst of spawns on the
        loop thread stalls heartbeats long enough for the GCS to declare
        this node dead (observed: actor churn → 5s+ gap → node DEAD).

        Startup concurrency is throttled per node (reference:
        maximum_startup_concurrency = num_cpus): an unthrottled 500-actor
        burst boots hundreds of Python processes at once, starving every
        daemon's heartbeat on a small host — nodes get declared dead at
        exactly the moment they're busiest."""
        await self._spawn_worker_throttled(job_id, runtime_env, pool_key)

    def _startup_sema(self) -> asyncio.Semaphore:
        if not hasattr(self, "_spawn_sema"):
            from ray_tpu._private.resources import CPU as _CPU

            self._spawn_sema = asyncio.Semaphore(
                max(2, int(self.local.total.get(_CPU) or 2)))
        return self._spawn_sema

    async def _spawn_worker_throttled(self, job_id: bytes,
                                      runtime_env: Optional[Dict[str, Any]],
                                      pool_key: bytes) -> None:
        log_dir = os.path.join(self.session_dir, "logs")
        worker_id = WorkerID.from_random()
        out_path = os.path.join(
            log_dir, f"worker-{worker_id.hex()[:12]}.out")
        err_path = os.path.join(
            log_dir, f"worker-{worker_id.hex()[:12]}.err")

        def _open_logs():
            # Sync file I/O belongs off the loop: on a loaded node (or a
            # network-backed session dir) mkdir/open stall for ms-class
            # latencies, and this coroutine shares its loop with lease
            # dispatch and heartbeats.
            os.makedirs(log_dir, exist_ok=True)
            out = open(out_path, "wb")
            # Separate stderr stream: tracebacks must reach the driver
            # tagged as stderr (and survive for exit forensics) instead
            # of being interleaved into stdout.
            err = open(err_path, "wb")
            return out, err

        out, err = await asyncio.get_running_loop().run_in_executor(
            None, _open_logs)
        env = self._worker_env()
        env_uris = []
        python_exe = sys.executable
        command_prefix = []
        if runtime_env:
            # Applied at worker spawn (reference: RuntimeEnvContext.exec_worker
            # runs the worker inside the env) — not mutated per-task. The
            # manager materializes pip venvs / code packages on pool miss.
            try:
                ctx = await self._runtime_env_manager().setup(runtime_env)
            except Exception as e:
                out.close()
                err.close()
                self._starting[pool_key] = max(
                    0, self._starting[pool_key] - 1)
                sys.stderr.write(f"[raylet] runtime_env setup failed: {e}\n")
                self._env_failures[pool_key] = (
                    f"{type(e).__name__}: {e}", time.monotonic())
                waiters = self._pending_pop[pool_key]
                while waiters:
                    fut = waiters.popleft()
                    if not fut.done():
                        fut.set_result(None)
                        break
                return
            for key, val in ctx.env_vars.items():
                env[str(key)] = str(val)
            if ctx.working_dir:
                env["RAY_TPU_WORKING_DIR"] = ctx.working_dir
            if ctx.pythonpath:
                env["PYTHONPATH"] = os.pathsep.join(
                    ctx.pythonpath
                    + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                       if p])
            if ctx.py_executable:
                python_exe = ctx.py_executable
                # The venv interpreter must still import ray_tpu itself.
                repo_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                env["PYTHONPATH"] = os.pathsep.join(
                    [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p] + [repo_root])
            command_prefix = list(ctx.command_prefix)
            if command_prefix:
                # Popen env applies to the container CLI, not inside the
                # container: graft the worker env through -e flags and use
                # the image's own interpreter.
                passthrough = dict(ctx.env_vars)
                for k in ("PYTHONPATH", "RAY_TPU_NODE_ID", "RAY_TPU_NODE_IP",
                          "RAY_TPU_WORKING_DIR"):
                    if env.get(k):
                        passthrough[k] = env[k]
                env_flags = []
                for k, v in passthrough.items():
                    env_flags += ["-e", f"{k}={v}"]
                command_prefix = (command_prefix[:-1] + env_flags
                                  + command_prefix[-1:])
                python_exe = "python3"
            env_uris = list(ctx.uris)
        cmd = command_prefix + [
               python_exe, "-m", "ray_tpu._private.worker_main",
               "--raylet-host", self.host,
               "--raylet-port", str(self.server.port),
               "--gcs-host", self.gcs_addr[0],
               "--gcs-port", str(self.gcs_addr[1]),
               "--node-id", self.node_id.hex(),
               "--worker-id", worker_id.hex(),
               "--job-id", job_id.hex(),
               "--raylet-pid", str(os.getpid()),
               "--session-dir", self.session_dir]
        loop = asyncio.get_running_loop()
        # The concurrency slot covers ONLY fork + interpreter boot — not
        # runtime_env setup above (a cold pip install holding a slot
        # would head-of-line block every plain spawn on the node).
        async with self._startup_sema():
            try:
                proc = await loop.run_in_executor(
                    None, lambda: subprocess.Popen(
                        cmd, stdout=out, stderr=err, env=env,
                        start_new_session=True))
            except Exception as e:
                err.close()
                return self._spawn_failed(e, out, pool_key, env_uris)
            # The child holds its own copies of the log fds now.
            out.close()
            err.close()
            # Handle is completed when the worker registers back.
            handle = _WorkerHandle(worker_id.binary(), proc, ("", 0),
                                   job_id, pool_key=pool_key,
                                   runtime_env=runtime_env)
            handle.env_uris = env_uris
            handle.out_path = out_path
            handle.err_path = err_path
            self.workers[worker_id.binary()] = handle
            # Hold the startup-concurrency slot until the worker
            # REGISTERS: the expensive part of a spawn is the Python
            # boot, not the fork. Bounded so a crashed boot frees the
            # slot (the reaper also sets the event on death).
            try:
                await asyncio.wait_for(handle.registered.wait(), 30)
            except asyncio.TimeoutError:
                pass
        return None

    def _spawn_failed(self, e, out, pool_key, env_uris) -> None:
        """Popen failure cleanup: undo the _starting slot, return env
        cache refs, and fail one parked lease waiter fast instead of
        letting it ride out the full pop timeout."""
        out.close()
        self._starting[pool_key] = max(0, self._starting[pool_key] - 1)
        sys.stderr.write(f"[raylet] worker spawn failed: {e}\n")
        if env_uris:
            try:
                self._runtime_env_manager().release(env_uris)
            except Exception:
                pass
        waiters = self._pending_pop[pool_key]
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    async def _h_register_worker(self, worker_id, port, pid, job_id):
        handle = self.workers.get(worker_id)
        if handle is None:
            return {"ok": False}
        handle.addr = (self.host, port)
        handle.registered.set()
        key = handle.pool_key
        self._env_failures.pop(key, None)
        self._starting[key] = max(0, self._starting[key] - 1)
        self._offer_worker(handle)
        return {"ok": True, "system_config": GlobalConfig.dump_system_config()}

    def _offer_worker(self, handle: _WorkerHandle):
        waiters = self._pending_pop[handle.pool_key]
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(handle)
                return
        # Pool hard cap: beyond max_workers idle processes per pool,
        # retire instead of hoarding — an idle worker is ~150 MB RSS
        # plus a heartbeat loop, and churn-heavy workloads otherwise
        # accumulate them without bound.
        if len(self._idle[handle.pool_key]) >= self._max_workers:
            self.workers.pop(handle.worker_id, None)
            self._release_worker_env(handle)
            self._intended_exit.add(handle.worker_id)
            try:
                self._retire_proc(handle.proc)
            except Exception:
                pass
            return
        handle.last_idle = time.monotonic()
        self._idle[handle.pool_key].append(handle)

    def _maybe_replenish(self, job_id: bytes,
                         runtime_env: Optional[Dict[str, Any]] = None
                         ) -> None:
        """Keep a floor of warm workers so the next actor creation (e.g.
        tune trials launched after kills) never serializes on a Python
        cold start."""
        pool_key = self._pool_key(job_id, runtime_env)
        # Workers still starting but already promised to waiting pops are
        # not warm capacity.
        warm = (len(self._idle[pool_key]) + self._starting[pool_key]
                - len(self._pending_pop[pool_key]))
        n_live = sum(1 for w in self.workers.values()
                     if w.job_id == job_id)
        want = GlobalConfig.worker_pool_min_idle
        while warm < want and n_live < self._max_workers:
            self._spawn_worker(job_id, runtime_env)
            warm += 1
            n_live += 1

    async def _pop_worker(self, job_id: bytes,
                          runtime_env: Optional[Dict[str, Any]] = None,
                          timeout: float = 60.0,
                          dedicated: bool = False
                          ) -> Optional[_WorkerHandle]:
        pool_key = self._pool_key(job_id, runtime_env)
        idle = self._idle[pool_key]
        while idle:
            handle = idle.popleft()
            if handle.proc.poll() is None:
                self._maybe_replenish(job_id, runtime_env)
                return handle
            self.workers.pop(handle.worker_id, None)
            self._release_worker_env(handle)
        # Count async-starting workers too: they only land in self.workers
        # after the off-loop Popen, so without _starting a request burst in
        # that window would overshoot the cap.
        n_live = sum(1 for w in self.workers.values()
                     if w.job_id == job_id)
        n_live += sum(v for k, v in self._starting.items()
                      if k[:len(job_id)] == job_id)
        if dedicated or n_live < self._max_workers:
            # Dedicated (actor) workers are admission-controlled by the
            # resource allocation that already succeeded, not by the
            # pooled-task-worker cap: 500 fractional-CPU actors on a
            # 2-CPU node are legal, and capping them at CPU*4 workers
            # wedges every actor past the cap in PENDING_CREATION.
            # Python worker cold-start is expensive; prestart a batch on first
            # demand so bursts don't serialize on process spawn (reference:
            # worker pool prestart, `worker_pool.cc`).
            n_spawn = 1
            if n_live == 0 and not runtime_env:
                n_spawn = min(GlobalConfig.worker_startup_batch,
                              self._max_workers)
            for _ in range(n_spawn):
                self._spawn_worker(job_id, runtime_env)
        fut = asyncio.get_running_loop().create_future()
        self._pending_pop[pool_key].append(fut)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None

    def _sweep_idle_ttl(self) -> None:
        """Enforce worker_pool_idle_ttl_s: pooled workers idle past the
        TTL are killed down to the warm floor. Without this, phase
        churn accumulates workers without bound (observed: 893 live
        worker processes after an actor storm — each one's idle
        heartbeat loop then taxes the whole host)."""
        ttl = GlobalConfig.worker_pool_idle_ttl_s
        if ttl <= 0:
            return
        now = time.monotonic()
        floor = GlobalConfig.worker_pool_min_idle
        for pool_key, idle in list(self._idle.items()):
            while len(idle) > floor and now - idle[0].last_idle > ttl:
                handle = idle.popleft()
                self.workers.pop(handle.worker_id, None)
                self._release_worker_env(handle)
                self._intended_exit.add(handle.worker_id)
                try:
                    self._retire_proc(handle.proc)
                except Exception:
                    pass

    def _retire_proc(self, proc) -> None:
        """Kill (if alive) and queue for reaping. Every removal path
        must route here: a kill() without a later wait() leaves a ZOMBIE
        child, and a 10^3-actor storm was observed to stack ~800 of them
        under the raylets (eventual PID exhaustion)."""
        try:
            if proc.poll() is None:
                proc.kill()
        except Exception:
            pass
        self._dying.append(proc)

    def _reap_dying(self) -> None:
        still = []
        for proc in self._dying:
            try:
                if proc.poll() is None:
                    still.append(proc)
            except Exception:
                pass
        self._dying = still

    def _classify_exit(self, worker_id: bytes, handle, code) -> Dict[str, Any]:
        """Waitpid-status exit taxonomy + last-K log line capture, cached
        for get_worker_exit_info (reference: WorkerExitType plumbing in
        worker-failure RPCs)."""
        from ray_tpu._private.log_monitor import tail_file
        from ray_tpu.observability import events as _events

        exit_type = _events.classify_worker_exit(
            code, oom_killed=worker_id in self._oom_killed,
            intended=worker_id in self._intended_exit,
            preempted=worker_id in self._preempted)
        self._intended_exit.discard(worker_id)
        # Marks for workers retired outside the reaper's view (popped
        # from self.workers before the kill) are never consumed; bound
        # the set so long-lived churny raylets don't grow it forever.
        if len(self._intended_exit) > 4096:
            self._intended_exit.clear()
        k = GlobalConfig.worker_exit_tail_lines
        info = {
            "exit_type": exit_type,
            "exit_code": code,
            "oom_killed": exit_type == "OOM_KILLED",
            "preempted": exit_type == "PREEMPT_RESCHEDULE",
            "pid": handle.proc.pid,
            "node_id": self.node_id.hex(),
            "last_lines": tail_file(handle.out_path, k)
            if handle.out_path else [],
            "last_err_lines": tail_file(handle.err_path, k)
            if handle.err_path else [],
        }
        self._exit_info[worker_id] = info
        while len(self._exit_info) > 1024:
            self._exit_info.pop(next(iter(self._exit_info)))
        return info

    def _observe_worker_death(self, worker_id: bytes, handle,
                              code) -> Dict[str, Any]:
        """Classify a worker death and report the WORKER_EXIT event —
        exactly once, whichever path saw the corpse first. The reaper's
        200ms poll usually loses the race to the owner's return_worker
        RPC (the owner sees the connection drop within ms), so without
        the return-path hook most task-worker crashes would vanish from
        the event log unclassified."""
        if worker_id in self._exit_info:
            return self._exit_info[worker_id]
        from ray_tpu.observability import events as _events

        info = self._classify_exit(worker_id, handle, code)
        exit_type = info["exit_type"]
        self._report_event(
            "WORKER_EXIT",
            f"worker {worker_id.hex()[:12]} (pid "
            f"{handle.proc.pid}) exited with code {code}: "
            f"{exit_type}",
            severity=_events.exit_severity(exit_type),
            worker_id=worker_id.hex(), pid=handle.proc.pid,
            exit_code=code, exit_type=exit_type,
            is_actor=handle.is_actor)
        return info

    async def _reaper_loop(self):
        """Detect dead worker processes; classify each exit from its
        waitpid status, capture log tails for forensics, report actor
        deaths (with the classification) and WORKER_EXIT events to GCS."""
        from ray_tpu.observability import events as _events

        last_ttl_sweep = time.monotonic()
        while not self._dead:
            await asyncio.sleep(0.2)
            self._reap_dying()
            if time.monotonic() - last_ttl_sweep > 5.0:
                last_ttl_sweep = time.monotonic()
                self._sweep_idle_ttl()
            for worker_id, handle in list(self.workers.items()):
                code = handle.proc.poll()
                if code is None:
                    continue
                handle.registered.set()  # frees the spawn-throttle slot
                self.workers.pop(worker_id, None)
                self._release_worker_env(handle)
                if handle.addr == ("", 0):
                    # Died before registering: undo its _starting slot or the
                    # warm-pool floor is suppressed forever.
                    self._starting[handle.pool_key] = max(
                        0, self._starting[handle.pool_key] - 1)
                for oid in self._client_mapped.pop(worker_id, ()):
                    self.store.release_client(oid)
                try:
                    self._idle[handle.pool_key].remove(handle)
                except ValueError:
                    pass
                if handle.is_actor:
                    # Replace the dead actor worker eagerly so the next
                    # actor creation finds a warm process.
                    self._maybe_replenish(handle.job_id, handle.runtime_env)
                if handle.lease is not None:
                    self._release_lease(handle)
                self._release_orphaned_leases(worker_id)
                # Classification mutates loop-confined death bookkeeping
                # and must land before the actor-death report below; the
                # blocking leaf is a bounded tail of a local log file.
                info = self._observe_worker_death(worker_id, handle, code)  # graftlint: disable=async-blocking-transitive
                exit_type = info["exit_type"]
                if handle.is_actor and handle.actor_id is not None:
                    cause = (f"worker process exited with code {code} "
                             f"[{exit_type}]")
                    detail = _events.format_exit_detail(info)
                    try:
                        await self.gcs.acall(
                            "report_actor_death", actor_id=handle.actor_id,
                            cause=cause + detail, timeout=10)
                    except Exception:
                        pass

    async def _log_monitor_loop(self):
        """Tail worker logs and publish new lines to drivers (reference:
        `_private/log_monitor.py:103` — how task `print`s reach the
        driver terminal)."""
        from ray_tpu._private.log_monitor import LogMonitor

        def info_of(wid_prefix: str):
            for worker_id, handle in self._worker_info_cache.items():
                if worker_id.hex().startswith(wid_prefix):
                    return handle
            return None

        def pid_of(wid_prefix: str):
            h = info_of(wid_prefix)
            return h.proc.pid if h else None

        monitor = LogMonitor(os.path.join(self.session_dir, "logs"),
                             pid_of=pid_of)
        while not self._dead:
            await asyncio.sleep(0.5)
            # Snapshot incl. recently-dead workers: their last lines must
            # still route to the right driver after the reaper pops them.
            for wid, h in self.workers.items():
                self._worker_info_cache[wid] = h
            while len(self._worker_info_cache) > 4096:
                self._worker_info_cache.pop(
                    next(iter(self._worker_info_cache)))
            for msg in monitor.scan():
                msg["ip"] = self.host
                msg["node_id"] = self.node_id.hex()
                h = info_of(msg["worker_id"])
                msg["job_id"] = h.job_id.hex() if h else None
                try:
                    await self.gcs.acall("publish", channel="logs",
                                         message=msg, timeout=10)
                except Exception:
                    pass

    async def _memory_monitor_loop(self):
        """OOM watchdog (reference: memory_monitor.h + worker_killing
        _policy.h): above the usage threshold, kill a leased task worker
        (newest lease first) so the task retries instead of the kernel
        OOM killer shooting the raylet or a TPU-holding actor."""
        from ray_tpu._private import memory_monitor

        period = GlobalConfig.memory_monitor_refresh_ms / 1000
        if period <= 0:
            return
        threshold = GlobalConfig.memory_usage_threshold
        test_path = GlobalConfig.memory_monitor_test_usage_path
        while not self._dead:
            await asyncio.sleep(period)
            usage = await asyncio.get_running_loop().run_in_executor(
                None, memory_monitor.usage_fraction, test_path)
            if usage is None:
                continue
            if usage <= threshold:
                # Below the kill threshold but above the preempt
                # threshold: reschedule the largest leased task worker
                # NOW, while there is still headroom, instead of waiting
                # to shoot it with OOM_KILLED semantics.
                preempt_thr = GlobalConfig.memory_preempt_threshold
                # _preempt_for_memory calls record_decision(emit=False):
                # the sync-RPC branch the linter sees through the chain
                # is dead here — the decision record is forwarded via
                # acall below it.
                if preempt_thr and preempt_thr < usage and \
                        self._preempt_for_memory(usage, preempt_thr):  # graftlint: disable=async-blocking-transitive
                    await asyncio.sleep(max(period, 1.0))
                continue
            victim = await self._pick_oom_victim()
            if victim is None:
                continue
            self._oom_kills += 1
            self._oom_killed.add(victim.worker_id)
            if len(self._oom_killed) > 1024:
                self._oom_killed.pop()
            sys.stderr.write(
                f"[raylet {self.node_id.hex()[:8]}] memory usage "
                f"{usage:.2f} > {threshold:.2f}: OOM-killing worker "
                f"pid={victim.proc.pid} (actor={victim.is_actor})\n")
            try:
                self._retire_proc(victim.proc)
            except Exception:
                pass
            # Let the reaper pick up the death before re-sampling, so one
            # spike doesn't massacre the whole pool.
            await asyncio.sleep(max(period, 1.0))

    def _pick_preempt_victim(self):
        """Largest-RSS leased TASK worker. Preemption exists to avoid
        OOM kills, and tasks reschedule for free via the owner's crash
        retry; actors lose state, so they are never preempted — the
        hard kill path still considers them as a last resort."""
        leased = [h for h in self.workers.values()
                  if h.lease is not None and not h.is_actor]
        if not leased:
            return None
        rss: Dict[bytes, float] = {}
        try:
            import psutil

            for h in leased:
                try:
                    rss[h.worker_id] = float(
                        psutil.Process(h.proc.pid).memory_info().rss)
                except Exception:
                    pass
        except Exception:
            pass
        if rss:
            return max(leased, key=lambda h: rss.get(h.worker_id, -1.0))
        return leased[-1]  # no RSS signal: newest lease loses least work

    def _preempt_for_memory(self, usage: float, threshold: float) -> bool:
        """PREEMPT_RESCHEDULE: retire the victim so its lease returns
        through the normal death path (reaper -> _release_lease) and the
        owner's retry loop reruns the task elsewhere. Returns True when
        a victim was actually preempted. Rate-limited by
        memory_preempt_cooldown_s; if usage keeps climbing past the kill
        threshold anyway, the next monitor tick falls back to the
        OOM-kill branch."""
        now = time.monotonic()
        if now - self._last_preempt_ts < \
                GlobalConfig.memory_preempt_cooldown_s:
            return False
        victim = self._pick_preempt_victim()
        if victim is None:
            return False
        self._last_preempt_ts = now
        self._preempts += 1
        self._preempted.add(victim.worker_id)
        if len(self._preempted) > 1024:
            self._preempted.pop()
        sys.stderr.write(
            f"[raylet {self.node_id.hex()[:8]}] memory usage "
            f"{usage:.2f} > preempt threshold {threshold:.2f}: "
            f"rescheduling worker pid={victim.proc.pid}\n")
        try:
            from ray_tpu.observability.control import record_decision

            # No global worker in a raylet: record_decision increments
            # the local counter (shipped with the next reporter-loop
            # metrics push) and we forward the decision record ourselves.
            payload = record_decision(
                "memory_preempt", "preempt_reschedule",
                "memory usage above preempt threshold",
                {"usage": round(usage, 3), "threshold": threshold,
                 "pid": victim.proc.pid,
                 "worker_id": victim.worker_id.hex()[:12]},
                node_id=self.node_id.hex(), emit=False)

            async def _send():
                try:
                    await self.gcs.acall("report_ctrl_decision",
                                         timeout=10, **payload)
                except Exception:
                    pass

            spawn_task(_send())
        except Exception:
            pass
        self._report_event(
            "PREEMPT_RESCHEDULE",
            f"memory usage {usage:.2f} above preempt threshold "
            f"{threshold:.2f}: rescheduling worker "
            f"{victim.worker_id.hex()[:12]} (pid {victim.proc.pid})",
            usage=round(usage, 3), threshold=threshold,
            pid=victim.proc.pid, worker_id=victim.worker_id.hex())
        try:
            self._retire_proc(victim.proc)
        except Exception:
            pass
        return True

    async def _reporter_loop(self):
        """Per-node resource reporter (reference: `dashboard/modules/
        reporter/reporter_agent.py:277`): node cpu/mem/disk, per-worker
        RSS, and TPU chip allocation, pushed as gauges through the
        existing metrics pipeline so they surface on the Prometheus
        endpoint and the dashboard."""
        try:
            import psutil
        except Exception:
            return
        node = self.node_id.hex()[:12]
        psutil.cpu_percent(interval=None)  # prime the sampler
        try:
            from ray_tpu.observability.object_store import (
                register_store_sampler,
            )
            from ray_tpu.util import metrics as _metrics

            register_store_sampler(self.store.stats, node)
        except Exception:
            _metrics = None

        def g(name, desc, tag_keys, data):
            return {"name": name, "type": "gauge", "description": desc,
                    "tag_keys": tuple(tag_keys), "default_tags": {},
                    "data": data}

        while not self._dead:
            await asyncio.sleep(GlobalConfig.metrics_report_interval_s)
            try:
                vm = psutil.virtual_memory()
                try:
                    disk = psutil.disk_usage(self.session_dir or "/")
                    disk_data = {f"{node},used": float(disk.used),
                                 f"{node},total": float(disk.total)}
                except Exception:
                    disk_data = {}
                rss = {}
                for h in list(self.workers.values()):
                    try:
                        rss[f"{node},{h.proc.pid}"] = float(
                            psutil.Process(h.proc.pid)
                            .memory_info().rss)
                    except Exception:
                        pass
                records = [
                    g("node_cpu_percent", "Node CPU utilization.",
                      ("node",), {node: psutil.cpu_percent(interval=None)}),
                    g("node_mem_used_bytes", "Node memory in use.",
                      ("node",), {node: float(vm.used)}),
                    g("node_mem_total_bytes", "Node memory capacity.",
                      ("node",), {node: float(vm.total)}),
                    g("node_disk_bytes",
                      "Session-dir filesystem usage by kind (used/total).",
                      ("node", "kind"), disk_data),
                    g("node_workers", "Live worker processes.",
                      ("node",), {node: float(len(self.workers))}),
                    g("node_tpu_chips_free", "Unassigned TPU chips.",
                      ("node",), {node: float(len(self._free_tpu_chips))}),
                    # NOT tag key "pid": the gauge renderer appends its
                    # own pid=<source> label to every gauge and duplicate
                    # label names break the whole Prometheus scrape.
                    g("worker_rss_bytes", "Per-worker resident memory.",
                      ("node", "worker_pid"), rss),
                ]
                if _metrics is not None:
                    # The raylet has no global worker, so the shared
                    # metrics flusher never runs here — ship the
                    # registry (the object-store gauges/counters fed by
                    # the store sampler) with the reporter push instead.
                    records.extend(_metrics.snapshot_records())
                await self.gcs.acall("push_metrics",
                                     source=f"reporter:{node}",
                                     records=records, timeout=10)
            except Exception:
                pass
            # Spill watermark -> SPILL_PRESSURE cluster event: one event
            # per batch of new spills, not one per poll.
            try:
                stats = self.store.stats()
                spills = int(stats.get("num_spills", 0))
                if spills > self._spills_reported:
                    self._report_event(
                        "SPILL_PRESSURE",
                        f"object store spilled "
                        f"{spills - self._spills_reported} object(s) "
                        f"({int(stats.get('spilled_bytes', 0))} bytes "
                        f"spilled since start)",
                        num_spills=spills,
                        spilled_bytes=int(stats.get("spilled_bytes", 0)))
                    self._spills_reported = spills
            except Exception:
                pass

    async def _h_profile_worker(self, worker_id=None, duration_s=5.0,
                                kind="profile", hz=None):
        """On-demand worker profiling (reference: `profile_manager.py`):
        forwards to the worker's sampling profiler / stack dumper /
        jax.profiler device-trace bracket (``kind`` = "profile" |
        "stacks" | "tpu_profile"). With no worker_id, covers every live
        worker on this node."""
        from ray_tpu._private.rpc import RpcClient

        targets = ([self.workers[worker_id]] if worker_id in self.workers
                   else list(self.workers.values()) if worker_id is None
                   else [])

        async def one(h):
            try:
                client = self._worker_probe_clients.get(h.worker_id)
                if client is None:
                    client = RpcClient(*h.addr)
                    self._worker_probe_clients[h.worker_id] = client
                if kind in ("stacks", "dump_stacks"):
                    reply = await client.acall("dump_stacks", timeout=10)
                elif kind == "tpu_profile":
                    reply = await asyncio.wait_for(
                        client.acall("tpu_profile", duration_s=duration_s,
                                     timeout=duration_s + 60),
                        duration_s + 60)
                else:
                    reply = await asyncio.wait_for(
                        client.acall("profile", duration_s=duration_s,
                                     hz=hz, timeout=duration_s + 30),
                        duration_s + 30)
                return h.worker_id.hex(), reply
            except Exception as e:  # noqa: BLE001
                return h.worker_id.hex(), {"error": repr(e)}

        # Concurrent: whole-node profiling takes ~duration_s, not
        # duration_s * n_workers (the dashboard RPC has a fixed budget).
        pairs = await asyncio.gather(
            *(one(h) for h in targets if h.addr != ("", 0)))
        return dict(pairs)

    async def _h_dump_stacks(self, worker_id=None):
        """One-shot cluster-stack fan-out (the `ray stack` node hop):
        every live worker's all-thread Python stacks, keyed by worker id
        hex. util.state.stack() calls this on one or every raylet."""
        return await self._h_profile_worker(worker_id=worker_id,
                                            kind="stacks")

    async def _pick_oom_victim(self):
        """Worker-killing policy (reference `worker_killing_policy.h:34`):
        among leased workers, prefer one actually executing (killing an
        idle pool worker frees no task memory), prefer retriable tasks
        over actors (tasks retry for free; actors lose state), newest
        lease first (loses the least progress). Busy state comes from a
        short `busy_info` probe; an unresponsive worker counts as busy —
        a thrashing process can't answer and is the likeliest hog."""
        from ray_tpu._private import memory_monitor
        from ray_tpu._private.rpc import RpcClient

        leased = [h for h in self.workers.values() if h.lease is not None]
        if not leased:
            return None

        async def probe(h):
            # Bound the WHOLE probe (connect included — acall's timeout
            # starts after connect, and connect retries up to 10s): the
            # monitor must pick a victim before the kernel OOM killer does.
            try:
                client = self._worker_probe_clients.get(h.worker_id)
                if client is None:
                    client = RpcClient(*h.addr)
                    self._worker_probe_clients[h.worker_id] = client
                info = await asyncio.wait_for(
                    client.acall("busy_info", timeout=1.0), 1.0)
                return h.worker_id if info.get("executing") else None
            except Exception:
                # Unresponsive = likeliest hog (a thrashing process can't
                # answer): count as busy.
                return h.worker_id
        hits = await asyncio.gather(*(probe(h) for h in leased))
        busy = {wid for wid in hits if wid is not None}
        # Per-worker RSS so the kill is attributed to the worker actually
        # holding the memory, not whichever leased newest.
        rss: Dict[bytes, float] = {}
        try:
            import psutil

            for h in leased:
                try:
                    rss[h.worker_id] = float(
                        psutil.Process(h.proc.pid).memory_info().rss)
                except Exception:
                    pass
        except Exception:
            pass
        return memory_monitor.pick_victim(leased, busy, rss)

    # ---------------------------------------------------------- lease protocol
    def _strategy_allows_local(self, strategy) -> bool:
        """May a queued request be granted on THIS node once resources free
        up?  Hard affinity/labels elsewhere must never fall back to local."""
        if strategy.kind == "NODE_AFFINITY":
            return strategy.node_id == self.node_id or strategy.soft
        if strategy.kind == "NODE_LABEL":
            from ray_tpu._private.scheduling_policy import _label_filter

            return self.node_id in _label_filter(self.view,
                                                 strategy.hard_labels)
        return True

    async def _h_request_worker_lease(self, demand, job_id, strategy_kind="DEFAULT",
                                      strategy_node=None, soft=False,
                                      hard_labels=None, soft_labels=None,
                                      lease_timeout=25.0, runtime_env=None,
                                      owner_id=None):
        """Returns {granted, worker_addr, worker_id, tpu_ids} |
        {spillback_to: addr} | {infeasible: True} | {timeout: True}."""
        from ray_tpu._private.task_spec import SchedulingStrategySpec

        timeout = lease_timeout
        demand_rs = ResourceSet(demand)
        strategy = SchedulingStrategySpec(kind=strategy_kind,
                                          node_id=strategy_node, soft=soft,
                                          hard_labels=hard_labels or {},
                                          soft_labels=soft_labels or {})
        # Fast path: local node can serve now (and the strategy permits it).
        if (strategy_kind in ("DEFAULT", "PLACEMENT_GROUP")
                and self.local.available.is_superset_of(demand_rs)):
            return await self._grant_local(demand_rs, job_id, timeout,
                                           strategy, runtime_env, owner_id)

        target = pick_node(self.view, demand_rs, strategy, self.node_id)
        if target == self.node_id:
            return await self._grant_local(demand_rs, job_id, timeout,
                                           strategy, runtime_env, owner_id)
        if target is not None:
            return {"spillback_to": self._node_addrs.get(target),
                    "spillback_node": target}
        # No node can serve *now*. Queue locally only if this node is both
        # feasible and allowed by the strategy; otherwise let the owner retry
        # (the right node's raylet will queue it when targeted directly).
        if (self.local.is_feasible(demand_rs)
                and self._strategy_allows_local(strategy)):
            fut = asyncio.get_running_loop().create_future()
            self._lease_queue.append((demand_rs, job_id, strategy, fut,
                                      runtime_env, owner_id))
            self._lease_queue_event.set()
            try:
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return {"timeout": True}
        if strategy.kind == "NODE_AFFINITY" and not strategy.soft:
            node = self.view.get(strategy.node_id)
            if node is None:
                # Target node unknown here — may be dead or just not yet in
                # this raylet's replicated view; let the owner retry until
                # its lease deadline rather than failing eagerly.
                return {"retry": True}
            if strategy.node_id != self.node_id:
                return {"spillback_to": self._node_addrs.get(strategy.node_id),
                        "spillback_node": strategy.node_id}
        if not is_feasible_anywhere(self.view, demand_rs):
            key = tuple(sorted(demand_rs.to_dict().items()))
            self._unfulfilled[key] = time.monotonic()
            return {"infeasible": True}
        return {"retry": True}

    async def _grant_local(self, demand: ResourceSet, job_id: bytes,
                           timeout: float, strategy=None, runtime_env=None,
                           owner_id=None):
        if runtime_env:
            failure = self._env_failures.get(
                self._pool_key(job_id, runtime_env))
            if failure is not None and time.monotonic() - failure[1] < 60:
                return {"env_setup_error": failure[0]}
        if not self.local.try_allocate(demand):
            fut = asyncio.get_running_loop().create_future()
            self._lease_queue.append((demand, job_id, strategy, fut,
                                      runtime_env, owner_id))
            self._lease_queue_event.set()
            try:
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return {"timeout": True}
        tpu_ids = self._take_tpu_chips(demand)
        handle = await self._pop_worker(job_id, runtime_env)
        if handle is None:
            self.local.release(demand)
            self._release_tpu_chips(demand, tpu_ids)
            return {"timeout": True}
        handle.lease = {"demand": demand, "tpu_ids": tpu_ids,
                        "owner_id": owner_id}
        handle.lease_ts = time.monotonic()
        handle.lease_epoch += 1
        return {"granted": True, "worker_addr": handle.addr,
                "worker_id": handle.worker_id, "tpu_ids": tpu_ids,
                "lease_token": handle.lease_epoch}

    @staticmethod
    def _pg_tpu_demand(demand: ResourceSet):
        """(quantity, pg_hex) for placement-group-formatted TPU names
        (``TPU_group_{i}_{pg}`` / ``TPU_group_{pg}``), or (0, None)."""
        for name in demand.names():
            if name.startswith(f"{TPU}_group_"):
                return demand.get(name), name.rsplit("_", 1)[-1]
        return 0.0, None

    def _take_tpu_chips(self, demand: ResourceSet) -> List[int]:
        pg_qty, pg_hex = self._pg_tpu_demand(demand)
        if pg_hex is not None:
            # Chips for PG-formatted leases come from the bundle's own
            # reserved pool — indexed and wildcard names share one pool, so
            # a bundle's chips can never be double-assigned, and the node's
            # free list is untouched.
            pool = self._bundle_tpu_pool(pg_hex)
            n = max(1, int(pg_qty)) if pg_qty > 0 else 0
            take, remainder = pool[:n], pool[n:]
            self._set_bundle_tpu_pool(pg_hex, remainder)
            return take
        qty = demand.get(TPU)
        n = int(qty)
        if n <= 0:
            if qty <= 0:
                return []
            # Fractional share: dedicate one chip to all fractional leases.
            if self._frac_chip is None:
                if not self._free_tpu_chips:
                    return []
                self._frac_chip = self._free_tpu_chips.pop(0)
            self._frac_users += 1
            return [self._frac_chip]
        if len(self._free_tpu_chips) < n:
            # Logical accounting granted more chips than physically free —
            # never hand out a short allocation silently.
            raise RuntimeError(
                f"TPU chip accounting out of sync: need {n}, free "
                f"{self._free_tpu_chips}")
        take, self._free_tpu_chips = (self._free_tpu_chips[:n],
                                      self._free_tpu_chips[n:])
        return take

    def _release_tpu_chips(self, demand: ResourceSet, chips: List[int]) -> None:
        pg_qty, pg_hex = self._pg_tpu_demand(demand)
        if pg_hex is not None:
            self._set_bundle_tpu_pool(
                pg_hex, sorted(self._bundle_tpu_pool(pg_hex) + list(chips)))
            return
        qty = demand.get(TPU)
        if 0 < qty < 1:
            if not chips:
                # The acquire returned [] (no chip was free); this lease
                # never became a fractional user — don't unbalance the count.
                return
            self._frac_users -= 1
            if self._frac_users <= 0 and self._frac_chip is not None:
                self._free_tpu_chips.append(self._frac_chip)
                self._free_tpu_chips.sort()
                self._frac_chip = None
                self._frac_users = 0
            return
        for c in chips:
            if c not in self._free_tpu_chips and c != self._frac_chip:
                self._free_tpu_chips.append(c)
        self._free_tpu_chips.sort()

    def _bundle_tpu_pool(self, pg_hex: str) -> List[int]:
        out = []
        for (pg_id, _idx), bundle in self._bundles.items():
            if pg_id.hex() == pg_hex:
                out.extend(bundle.get("tpu_chips", []))
        return sorted(out)

    def _set_bundle_tpu_pool(self, pg_hex: str, chips: List[int]) -> None:
        """Redistribute the pool across the pg's bundles (pool is logically
        per-PG on this node; storage is per-bundle for return_bundle)."""
        chips = list(chips)
        entries = [(key, b) for key, b in self._bundles.items()
                   if key[0].hex() == pg_hex]
        for i, (key, bundle) in enumerate(entries):
            if i == len(entries) - 1:
                bundle["tpu_chips"] = chips
                chips = []
            else:
                cap = int(bundle["resources"].get(TPU))
                bundle["tpu_chips"] = chips[:cap]
                chips = chips[cap:]

    def _release_lease(self, handle: _WorkerHandle):
        lease = handle.lease
        handle.lease = None
        if lease is None:
            return
        self.local.release(lease["demand"])
        self._release_tpu_chips(lease["demand"], lease["tpu_ids"])
        self._lease_queue_event.set()

    def _release_orphaned_leases(self, owner_id: bytes) -> None:
        """Reclaim task-worker leases whose *owner* worker died on this
        node.  Leases are normally returned by the owner's idle sweeper,
        but a force-killed owner (e.g. ``ray_tpu.kill`` of an actor that
        was mid-stream driving remote tasks) never gets to return them —
        observed as a streaming_split coordinator kill landing inside the
        owner's 0.5s lease-idle window and permanently leaking the leased
        CPUs, wedging every later lease request on the saturated node.
        Dedicated actor workers are excluded: their lifetime belongs to
        the GCS actor manager, not to a task lease."""
        if not owner_id:
            return
        for h in list(self.workers.values()):
            if (h.is_actor or h.lease is None
                    or h.lease.get("owner_id") != owner_id):
                continue
            sys.stderr.write(
                f"[raylet] reclaiming lease of worker "
                f"{h.worker_id.hex()[:12]}: owner "
                f"{owner_id.hex()[:12]} died\n")
            self._report_event(
                "LEASE_RECLAIMED",
                f"reclaimed lease of worker {h.worker_id.hex()[:12]}: "
                f"owner {owner_id.hex()[:12]} died",
                worker_id=h.worker_id.hex(), owner_id=owner_id.hex())
            self._release_lease(h)
            # The worker may still be executing a push from the dead
            # owner; its results have nowhere to go, so retire the
            # process rather than re-offering it mid-task.
            self.workers.pop(h.worker_id, None)
            self._release_worker_env(h)
            self._intended_exit.add(h.worker_id)
            self._retire_proc(h.proc)

    async def _lease_dispatch_loop(self):
        """Re-schedule queued lease requests whenever resources free up or the
        cluster view changes — including spilling a queued task to another
        node that became (or became known to be) available, mirroring the
        reference's ScheduleAndDispatchTasks re-runs."""
        from ray_tpu._private.task_spec import SchedulingStrategySpec

        default = SchedulingStrategySpec()
        while not self._dead:
            try:
                await asyncio.wait_for(self._lease_queue_event.wait(), 0.1)
            except asyncio.TimeoutError:
                pass
            self._lease_queue_event.clear()
            pending = len(self._lease_queue)
            for _ in range(pending):
                (demand, job_id, strategy, fut,
                 runtime_env, owner_id) = self._lease_queue.popleft()
                if fut.done():
                    continue
                if self.local.available.is_superset_of(demand):
                    reply = await self._grant_local(demand, job_id, 60.0,
                                                    strategy, runtime_env,
                                                    owner_id)
                    if not fut.done():
                        fut.set_result(reply)
                    continue
                target = pick_node(self.view, demand, strategy or default,
                                   self.node_id)
                if (target is not None and target != self.node_id
                        and target in self._node_addrs):
                    if not fut.done():
                        fut.set_result(
                            {"spillback_to": self._node_addrs[target],
                             "spillback_node": target})
                    continue
                self._lease_queue.append((demand, job_id, strategy, fut,
                                          runtime_env, owner_id))
            await asyncio.sleep(0.005)

    async def _h_return_worker(self, worker_id, kill=False,
                               lease_token=None):
        handle = self.workers.get(worker_id)
        if handle is None:
            return False
        # Reject stale returns: a return frame can be processed long
        # after it was sent (busy raylet), by which time the worker may
        # hold a NEWER lease — possibly as a dedicated actor. Applying
        # the stale return would strip that lease, re-offer the worker
        # to the idle pool, and let a later task-lease failure SIGKILL
        # a live actor.
        if lease_token is not None and lease_token != handle.lease_epoch:
            return False
        if handle.is_actor:
            # Task-lease returns never apply to dedicated actor workers
            # (defense in depth for token-less callers).
            sys.stderr.write(
                f"[raylet] ignoring return_worker for actor worker "
                f"{worker_id.hex()[:12]}\n")
            return False
        self._release_lease(handle)
        code = handle.proc.poll()
        if code is None and (worker_id in self._oom_killed
                             or worker_id in self._preempted):
            # The memory monitor shot this worker and its SIGKILL is
            # still in flight: the owner's ConnectionLost discard beat
            # waitpid. Taking the kill branch below would mark the death
            # INTENDED_EXIT and pop the handle before anyone classified
            # it — the OOM would vanish from the event log. Leave the
            # corpse-to-be in self.workers; the reaper's poll classifies
            # and reports it within a tick.
            return True
        if kill or code is not None:
            self.workers.pop(worker_id, None)
            self._release_worker_env(handle)
            if code is None:
                self._intended_exit.add(worker_id)
                self._retire_proc(handle.proc)
            else:
                # The worker is already a corpse: the owner noticed the
                # crash and returned the lease before the reaper's poll.
                # Classify + report here or the death never hits the
                # event log. Loop-confined bookkeeping; the blocking leaf
                # is a bounded tail of a local log file.
                self._observe_worker_death(worker_id, handle, code)  # graftlint: disable=async-blocking-transitive
        else:
            self._offer_worker(handle)
        return True

    async def _h_lease_worker_for_actor(self, spec, demand):
        demand_rs = ResourceSet(demand)
        renv = getattr(spec, "runtime_env", None)
        if renv:
            failure = self._env_failures.get(
                self._pool_key(spec.job_id.binary(), renv))
            if failure is not None and time.monotonic() - failure[1] < 60:
                return {"ok": False, "env_setup_error": failure[0],
                        "reason": f"runtime_env setup failed: {failure[0]}"}
        if not self.local.try_allocate(demand_rs):
            return {"ok": False, "reason": "resources busy"}
        tpu_ids = self._take_tpu_chips(demand_rs)
        handle = await self._pop_worker(spec.job_id.binary(),
                                        getattr(spec, "runtime_env", None),
                                        dedicated=True)
        if handle is None:
            self.local.release(demand_rs)
            self._release_tpu_chips(demand_rs, tpu_ids)
            return {"ok": False, "reason": "no worker"}
        handle.lease = {"demand": demand_rs, "tpu_ids": tpu_ids}
        handle.lease_ts = time.monotonic()
        handle.lease_epoch += 1
        handle.is_actor = True
        handle.actor_id = spec.actor_id.binary()
        return {"ok": True, "worker_addr": handle.addr,
                "worker_id": handle.worker_id, "tpu_ids": tpu_ids}

    async def _h_worker_exiting(self, worker_id):
        self._intended_exit.add(worker_id)
        handle = self.workers.pop(worker_id, None)
        if handle is not None:
            self._release_lease(handle)
            self._release_worker_env(handle)
            try:
                self._idle[handle.pool_key].remove(handle)
            except ValueError:
                pass
            self._release_orphaned_leases(worker_id)
        return True

    async def _h_kill_worker(self, worker_id, force=True):
        handle = self.workers.get(worker_id)
        if handle is None:
            return False
        # A kill the framework itself issued must not read as
        # SYSTEM_ERROR when the reaper classifies the SIGKILL.
        self._intended_exit.add(worker_id)
        if force:
            self._retire_proc(handle.proc)
        else:
            try:
                handle.proc.terminate()  # graceful; the reaper collects it
            except Exception:
                pass
            self._dying.append(handle.proc)
        return True

    # ------------------------------------------------------------ object store
    async def _h_create_object(self, object_id, size):
        path, offset = await self.store.create_async(object_id, size)
        return {"path": path, "offset": offset}

    async def _h_seal_object(self, object_id, pin=False):
        self.store.seal(object_id)
        if pin:
            self.store.pin(object_id)
        return True

    async def _h_put_object(self, object_id, payload, pin=False):
        """One-RPC put for small/medium objects: create+write+seal(+pin).

        The payload rides the RPC frame (one extra copy) in exchange for a
        single round trip — the client-side 3-RPC create/seal/pin dance
        dominated small-put latency (reference bar: ray_perf.py put suites).
        """
        await self.store.put_bytes_async(object_id, payload)
        if pin:
            self.store.pin(object_id)
        return True

    def _track_client_ref(self, object_id, client_id) -> None:
        self.store.addref_client(object_id)
        if client_id:
            self._client_mapped[client_id].add(object_id)

    async def _h_get_object(self, object_id, wait_timeout=None, locations=None,
                            client_id=None):
        timeout = wait_timeout
        """Wait locally; if absent and locations are known, pull from a
        remote raylet in chunks (reference: PullManager + ObjectManager)."""
        found = await self.store.get(object_id, timeout=0.0)
        if found is not None:
            self._track_client_ref(object_id, client_id)
            return {"path": found[0], "size": found[1],
                    "offset": found[2]}
        if locations:
            for node_id in locations:
                if node_id == self.node_id:
                    continue
                addr = self._node_addrs.get(node_id)
                if addr is None:
                    continue
                try:
                    await self._pull_from(object_id, addr)
                    found = await self.store.get(object_id, timeout=1.0)
                    if found is not None:
                        self._track_client_ref(object_id, client_id)
                        return {"path": found[0], "size": found[1],
                                "offset": found[2]}
                except Exception:
                    continue
            # The owner's directory said where the copies are and every
            # pull failed (nodes dead / object gone). Fail fast: the owner
            # can reconstruct via lineage; blocking the full client timeout
            # here just delays recovery.
            found = await self.store.get(object_id,
                                         timeout=min(timeout or 2.0, 2.0))
        else:
            found = await self.store.get(object_id, timeout=timeout)
        if found is None:
            return {"not_found": True}
        self._track_client_ref(object_id, client_id)
        return {"path": found[0], "size": found[1], "offset": found[2]}

    async def _pull_from(self, object_id, addr: Tuple[str, int]):
        client = self._remote_client(addr)
        info = await client.acall("object_info", object_id=object_id,
                                  timeout=30)
        if info is None:
            raise KeyError("remote object gone")
        size = info["size"]
        chunk = GlobalConfig.object_manager_chunk_size
        await self.store.create_async(object_id, size)
        for offset in range(0, size, chunk):
            data = await client.acall(
                "read_chunk", object_id=object_id, offset=offset,
                length=min(chunk, size - offset), timeout=60)
            self.store.write_into(object_id, offset, data)
        self.store.seal(object_id)

    def _remote_client(self, addr) -> RpcClient:
        addr = tuple(addr)
        if addr not in self._remote_raylets:
            self._remote_raylets[addr] = RpcClient(*addr)
        return self._remote_raylets[addr]

    async def _h_release_object(self, object_id, client_id=None):
        self.store.release_client(object_id)
        if client_id:
            self._client_mapped[client_id].discard(object_id)
        return True

    async def _h_release_objects(self, object_ids, client_id=None):
        for oid in object_ids:
            self.store.release_client(oid)
            if client_id:
                self._client_mapped[client_id].discard(oid)
        return True

    async def _h_contains_object(self, object_id):
        return self.store.contains(object_id)

    async def _h_object_info(self, object_id):
        if not self.store.contains(object_id):
            return None
        return {"size": self.store.size_of(object_id)}

    async def _h_read_chunk(self, object_id, offset, length):
        return self.store.read_bytes(object_id, offset, length)

    async def _h_delete_objects(self, object_ids):
        self.store.delete(object_ids)
        return True

    async def _h_pin_object(self, object_id):
        self.store.pin(object_id)
        return True

    async def _h_unpin_object(self, object_id):
        self.store.unpin(object_id)
        return True

    async def _h_store_stats(self):
        return self.store.stats()

    async def _h_memory_stats(self, top_n=50):
        """One-shot memory introspection snapshot for `memory_summary()`
        and `GET /api/memory`: the store's aggregate stats plus the
        largest objects it is tracking."""
        return {"store": self.store.stats(),
                "objects": self.store.object_table(int(top_n) or 50)}

    # -------------------------------------------------------------- PG bundles
    async def _h_prepare_bundle(self, pg_id, bundle_index, resources):
        """Phase 1: reserve the bundle's resources (reversible)."""
        key = (pg_id, bundle_index)
        if key in self._bundles:
            return True
        demand = ResourceSet(resources)
        if not self.local.try_allocate(demand):
            return False
        # Reserve physical TPU chips for the bundle now; PG-formatted leases
        # later draw from this pool instead of the node's free list.
        tpu_chips = self._take_tpu_chips(demand)
        self._bundles[key] = {"resources": demand, "committed": False,
                              "tpu_chips": tpu_chips}
        return True

    async def _h_commit_bundle(self, pg_id, bundle_index):
        """Phase 2: mint the bundle-formatted resources on this node
        (reference formatted-resource scheme: `CPU_group_{i}_{pg}` etc.)."""
        from ray_tpu._private.resources import pg_bundle_grant

        key = (pg_id, bundle_index)
        bundle = self._bundles.get(key)
        if bundle is None or bundle["committed"]:
            return bundle is not None
        add = pg_bundle_grant(bundle["resources"], pg_id.hex(), bundle_index)
        self.local.total = self.local.total.add(add)
        self.local.available = self.local.available.add(add)
        bundle["committed"] = True
        bundle["formatted"] = add
        self._lease_queue_event.set()
        return True

    async def _h_return_bundle(self, pg_id, bundle_index):
        key = (pg_id, bundle_index)
        bundle = self._bundles.pop(key, None)
        if bundle is None:
            return True
        for c in bundle.get("tpu_chips", []):
            if c not in self._free_tpu_chips:
                self._free_tpu_chips.append(c)
        self._free_tpu_chips.sort()
        if bundle["committed"]:
            add = bundle["formatted"]
            self.local.total = self.local.total.subtract(add)
            self.local.available = self.local.available.subtract(add)
            # Clamp negatives (a task may still hold formatted resources).
            if self.local.available.has_negative():
                fixed = {k: max(0, v) for k, v in
                         self.local.available._fixed.items()}
                self.local.available = ResourceSet(_fixed=fixed)
        self.local.release(bundle["resources"])
        return True

    # ------------------------------------------------------------------- misc
    async def _h_node_stats(self):
        return {
            "node_id": self.node_id,
            "resources_total": self.local.total.to_dict(),
            "resources_available": self.local.available.to_dict(),
            "num_workers": len(self.workers),
            "store": self.store.stats(),
            "event_stats": self.server.stats.snapshot(),
            "oom_kills": self._oom_kills,
            "memory_preempts": self._preempts,
        }

    async def _h_get_worker_exit_info(self, worker_id):
        """Why did this worker die? Lets the owner raise OutOfMemoryError
        instead of a generic WorkerCrashedError, and enrich the death
        error with the exit classification + the worker's last log lines
        (reference: exit-type plumbing in worker failure RPCs)."""
        info = dict(self._exit_info.get(worker_id) or {})
        info["oom_killed"] = (info.get("oom_killed", False)
                              or worker_id in self._oom_killed)
        info["preempted"] = (info.get("preempted", False)
                             or worker_id in self._preempted)
        return info

    async def _h_get_log(self, worker_id=None, task_id=None, tail=100):
        """Per-task / per-worker log retrieval over the raylet (reference:
        `ListLogs`/`StreamLog` in the reference dashboard agent). Log
        files outlive their workers, so this serves dead workers too —
        exactly the ones a postmortem cares about. Returns {"lines":
        [...]} where stderr lines follow stdout lines per file."""
        from ray_tpu._private import log_monitor

        tail = max(int(tail), 0)
        log_dir = os.path.join(self.session_dir, "logs") \
            if self.session_dir else ""

        def _scan() -> List[str]:
            # Pure file reads over an arbitrary number of worker logs:
            # runs in the executor so a fat log can't stall the raylet.
            lines: List[str] = []
            if worker_id is not None:
                wid_hex = worker_id.hex() if isinstance(worker_id, bytes) \
                    else str(worker_id)
                prefix = wid_hex[:12]
                for suffix in (".out", ".err"):
                    path = os.path.join(log_dir, f"worker-{prefix}{suffix}")
                    got = log_monitor.read_task_lines(
                        path, task_id_hex=None, max_lines=tail)
                    if got and suffix == ".err":
                        lines.extend(f"[stderr] {ln}" for ln in got)
                    else:
                        lines.extend(got)
            elif task_id is not None:
                tid_hex = task_id.hex() if isinstance(task_id, bytes) \
                    else str(task_id)
                try:
                    names = sorted(os.listdir(log_dir))
                except OSError:
                    names = []
                for name in names:
                    if not (name.startswith("worker-")
                            and name.endswith((".out", ".err"))):
                        continue
                    got = log_monitor.read_task_lines(
                        os.path.join(log_dir, name), task_id_hex=tid_hex,
                        max_lines=tail)
                    if got and name.endswith(".err"):
                        lines.extend(f"[stderr] {ln}" for ln in got)
                    else:
                        lines.extend(got)
            return lines

        lines = await asyncio.get_running_loop().run_in_executor(None, _scan)
        if tail:
            lines = lines[-tail:]
        return {"lines": lines}

    async def _h_get_tasks_info(self):
        out = []
        for w in self.workers.values():
            if w.lease is not None:
                out.append({"worker_id": w.worker_id, "is_actor": w.is_actor,
                            "actor_id": w.actor_id})
        return out

    async def _h_shutdown_node(self):
        asyncio.get_running_loop().call_later(0.05, self.shutdown)
        return True

    def shutdown(self):
        self._dead = True
        for handle in self.workers.values():
            try:
                self._retire_proc(handle.proc)
            except Exception:
                pass
        self.store.cleanup()
        os._exit(0)


def main():
    # SIGUSR1 dumps all thread stacks to the daemon log (see gcs_server).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    # SIGUSR2 dumps parked-coroutine stacks + submit-queue state for
    # every event loop — faulthandler can't see awaits (rpc.py).
    from ray_tpu._private.rpc import install_coroutine_dump_signal
    install_coroutine_dump_signal()

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--resources", required=True)
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--object-store-capacity", type=int, default=0)
    parser.add_argument("--fate-share-pid", type=int, default=0)
    args = parser.parse_args()

    capacity = args.object_store_capacity or GlobalConfig.object_store_memory
    import signal

    raylet = Raylet(
        node_id=bytes.fromhex(args.node_id),
        host=args.host,
        gcs_addr=(args.gcs_host, args.gcs_port),
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        session_dir=args.session_dir,
        object_store_capacity=capacity,
        port=args.port,
    )
    # Graceful termination must clean the node's /dev/shm store files.
    signal.signal(signal.SIGTERM, lambda *_: raylet.shutdown())
    from ray_tpu._private.fate_share import watch_parent

    # Clean the object store before exiting on spawner death too.
    watch_parent(args.fate_share_pid, on_death=raylet.shutdown)
    port = raylet.start()
    print(f"RAYLET_PORT={port}", flush=True)
    import threading
    threading.Event().wait()


if __name__ == "__main__":
    main()
