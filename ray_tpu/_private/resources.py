"""Resource model: fixed-point resource arithmetic over named resources.

Mirrors the reference's scheduling substrate (`scheduling_ids.h:35`
`PredefinedResourcesEnum`, `fixed_point.h`, `cluster_resource_data.h`) with TPU
promoted to a predefined resource: {CPU, MEM, TPU, OBJECT_STORE_MEM} plus
arbitrary custom string resources (e.g. ``TPU-v5e-16-head`` pod-gang markers).

All quantities are fixed-point with 1/10000 granularity so that fractional
requests (num_cpus=0.5) compose without float drift — the same trick as the
reference's `FixedPoint`.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping

RESOLUTION = 10_000

CPU = "CPU"
MEM = "memory"
TPU = "TPU"
OBJECT_STORE_MEM = "object_store_memory"

PREDEFINED = (CPU, MEM, TPU, OBJECT_STORE_MEM)

# Custom resources implicitly attached to TPU hosts (see accelerators/tpu.py):
# "TPU-<type>" (e.g. TPU-v5e), "TPU-<type>-<topo>-head" for pod slice heads,
# and one resource named after the pod slice for gang co-location.


def to_fixed(value: float) -> int:
    return round(value * RESOLUTION)


def from_fixed(value: int) -> float:
    return value / RESOLUTION


class ResourceSet:
    """Immutable-ish map of resource name -> fixed-point quantity.

    Zero-valued entries are dropped, so an empty set means "no resources".
    """

    __slots__ = ("_fixed",)

    def __init__(self, quantities: Mapping[str, float] | None = None, *, _fixed=None):
        if _fixed is not None:
            self._fixed: Dict[str, int] = {k: v for k, v in _fixed.items() if v != 0}
        else:
            self._fixed = {
                name: to_fixed(qty)
                for name, qty in (quantities or {}).items()
                if to_fixed(qty) != 0
            }

    # -- accessors ----------------------------------------------------------
    def get(self, name: str) -> float:
        return from_fixed(self._fixed.get(name, 0))

    def get_fixed(self, name: str) -> int:
        return self._fixed.get(name, 0)

    def names(self) -> Iterable[str]:
        return self._fixed.keys()

    def is_empty(self) -> bool:
        return not self._fixed

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._fixed.items()}

    # -- arithmetic ---------------------------------------------------------
    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._fixed)
        for k, v in other._fixed.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet(_fixed=out)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._fixed)
        for k, v in other._fixed.items():
            out[k] = out.get(k, 0) - v
        return ResourceSet(_fixed=out)

    def is_superset_of(self, demand: "ResourceSet") -> bool:
        return all(self._fixed.get(k, 0) >= v for k, v in demand._fixed.items())

    def has_negative(self) -> bool:
        return any(v < 0 for v in self._fixed.values())

    # -- comparison / misc --------------------------------------------------
    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._fixed == other._fixed

    def __hash__(self):
        return hash(frozenset(self._fixed.items()))

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (_resource_set_from_fixed, (dict(self._fixed),))


def _resource_set_from_fixed(fixed):
    return ResourceSet(_fixed=fixed)


def pg_task_demand(demand: "ResourceSet", pg_hex: str,
                   bundle_index: int) -> "ResourceSet":
    """Rewrite a task's demand onto placement-group bundle-formatted
    resources (reference scheme: tasks inside a PG consume
    ``{name}_group_{index}_{pg_id}`` / ``{name}_group_{pg_id}``).

    Single source of truth for the formatted-resource naming — used by both
    the owner-side submitter and the GCS actor scheduler.
    """
    out = {}
    for name, qty in demand.to_dict().items():
        if bundle_index >= 0:
            out[f"{name}_group_{bundle_index}_{pg_hex}"] = qty
        else:
            out[f"{name}_group_{pg_hex}"] = qty
    if not out:
        # Zero-resource tasks still anchor to the PG's wildcard resource.
        out[f"bundle_group_{pg_hex}"] = 0.001
    return ResourceSet(out)


def pg_bundle_grant(bundle_resources: "ResourceSet", pg_hex: str,
                    bundle_index: int) -> "ResourceSet":
    """The formatted resources a raylet mints when committing a bundle."""
    out = {}
    for name, qty in bundle_resources.to_dict().items():
        out[f"{name}_group_{bundle_index}_{pg_hex}"] = qty
        out[f"{name}_group_{pg_hex}"] = qty
    out[f"bundle_group_{bundle_index}_{pg_hex}"] = 1000
    out[f"bundle_group_{pg_hex}"] = 1000
    return ResourceSet(out)


class NodeResources:
    """A node's total and available resources plus labels.

    Utilization math backs the hybrid scheduling policy (reference:
    `hybrid_scheduling_policy.h:29-48`): the *critical resource utilization*
    of a node is max over resources of used/total.
    """

    def __init__(self, total: ResourceSet, labels: Dict[str, str] | None = None):
        self.total = total
        self.available = total
        self.labels = labels or {}

    def try_allocate(self, demand: ResourceSet) -> bool:
        if not self.available.is_superset_of(demand):
            return False
        self.available = self.available.subtract(demand)
        return True

    def release(self, demand: ResourceSet) -> None:
        self.available = self.available.add(demand)
        # Guard against double-release pushing past total.
        for name in list(self.available.names()):
            if self.available.get_fixed(name) > self.total.get_fixed(name):
                fixed = dict(self.available._fixed)
                fixed[name] = self.total.get_fixed(name)
                self.available = ResourceSet(_fixed=fixed)

    def is_feasible(self, demand: ResourceSet) -> bool:
        """Could this node EVER run the demand (ignoring current usage)?"""
        return self.total.is_superset_of(demand)

    def critical_utilization(self) -> float:
        best = 0.0
        for name in self.total.names():
            total = self.total.get_fixed(name)
            if total <= 0 or name == OBJECT_STORE_MEM:
                continue
            used = total - self.available.get_fixed(name)
            best = max(best, used / total)
        return best

    def to_dict(self):
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }

    @classmethod
    def from_dict(cls, d) -> "NodeResources":
        nr = cls(ResourceSet(d["total"]), d.get("labels"))
        nr.available = ResourceSet(d["available"])
        return nr
