"""GCS — the head-node control plane.

Role-equivalent to the reference's `src/ray/gcs/gcs_server/gcs_server.cc:187-232`
which installs node / resource / health / job / actor / placement-group / KV /
pubsub / task-event managers. One GCS per cluster, run as its own process
(``python -m ray_tpu._private.gcs_server``). State lives in an in-memory store
(the reference's default `gcs_storage="memory"`), with a periodic
file-backed snapshot of the durable tables (KV, jobs, named-actor registry)
so a restarted GCS recovers them (reference analog:
`store_client/redis_store_client.h:33` — Redis-backed FT).

Actors are scheduled *centrally* here (reference: `gcs_actor_scheduler.cc:49`),
unlike normal tasks which use the distributed raylet lease protocol.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.rpc import RpcClient, RpcServer, get_io_loop, spawn_task
from ray_tpu._private.scheduling_policy import ClusterView, pick_node
from ray_tpu._private.task_spec import SchedulingStrategySpec

ALIVE = "ALIVE"
DEAD = "DEAD"

# Actor lifecycle states (reference: src/ray/design_docs/actor_states.rst)
PENDING_CREATION = "PENDING_CREATION"
RESTARTING = "RESTARTING"


class Pubsub:
    """Long-poll pub/sub (reference: `src/ray/pubsub/`)."""

    def __init__(self):
        self._channels: Dict[str, List[Tuple[int, Any]]] = defaultdict(list)
        self._events: Dict[str, asyncio.Event] = defaultdict(asyncio.Event)
        self._seq = 0
        self.on_publish = None   # hook: snapshot dirty-marking

    def publish(self, channel: str, message: Any) -> None:
        if self.on_publish is not None:
            self.on_publish(channel)
        self._seq += 1
        log = self._channels[channel]
        log.append((self._seq, message))
        if len(log) > 10000:
            del log[: len(log) - 10000]
        ev = self._events[channel]
        ev.set()
        self._events[channel] = asyncio.Event()

    async def poll(self, channel: str, cursor: int, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            msgs = [(s, m) for s, m in self._channels[channel] if s > cursor]
            if msgs:
                return msgs
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            try:
                await asyncio.wait_for(
                    asyncio.shield(self._events[channel].wait()), remaining)
            except asyncio.TimeoutError:
                return []


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.server = RpcServer(host, port)
        self.view = ClusterView()
        # Bumped whenever the nodes snapshot would change (membership or a
        # node's resource availability). Raylets echo the last seq they
        # applied; heartbeat replies carry a fresh snapshot only when it
        # advanced — at a 100ms report period an idle cluster would
        # otherwise serialize O(nodes) snapshots to every raylet 10x/s.
        self._view_seq = 1
        self.pubsub = Pubsub()

        # node_id(bytes) -> node info dict
        self.nodes: Dict[bytes, Dict[str, Any]] = {}
        self._node_clients: Dict[bytes, RpcClient] = {}
        self._last_heartbeat: Dict[bytes, float] = {}

        # actors
        self.actors: Dict[bytes, Dict[str, Any]] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        self._actor_events: Dict[bytes, asyncio.Event] = {}

        # kv: namespace -> key -> bytes
        self.kv: Dict[str, Dict[str, bytes]] = defaultdict(dict)

        # placement groups
        self.placement_groups: Dict[bytes, Dict[str, Any]] = {}

        # jobs
        self._next_job_int = 0
        self.jobs: Dict[bytes, Dict[str, Any]] = {}

        # task events ring buffer (reference: gcs_task_manager.h:85)
        self.task_events: deque = deque(
            maxlen=GlobalConfig.task_events_buffer_size)
        # Monotone per-state totals of everything ever pushed, so the
        # rtpu_tasks_events_total exposition has counter semantics (the
        # ring buffer itself shrinks as entries fall out).
        self._task_event_counts: Dict[str, int] = defaultdict(int)

        # Cluster event log (reference: event.proto structured export):
        # typed, severity-tagged failure-forensics events in a bounded
        # ring; severities/types validated against the schema registry.
        self.cluster_events: deque = deque(
            maxlen=GlobalConfig.cluster_events_buffer_size)
        self._event_seq = 0
        # (type, severity) -> monotone count for the Prometheus counter.
        self._event_counts: Dict[Tuple[str, str], int] = defaultdict(int)

        # internal worker info registry (worker_id -> info)
        self.workers: Dict[bytes, Dict[str, Any]] = {}

        # user-defined metrics: source (pid string) -> (ts, snapshots)
        # (reference: per-node MetricsAgent registry aggregated by
        # Prometheus). Entries expire when a source stops pushing — the
        # same visibility a Prometheus target losing a process has;
        # counter resets are the scrape consumer's problem (rate()).
        self.user_metrics: Dict[str, Tuple[float, List[Dict[str, Any]]]] = {}
        # Monotonic series (counters/histograms) of expired sources, folded
        # here so cluster totals never go backwards when a worker exits.
        self._metric_tombstones: Dict[str, Dict[str, Any]] = {}

        # Request-scoped traces: trace-tagged SPAN events peeled off
        # push_task_events accumulate here until the root span arrives,
        # then tail-sample (observability/traces.py). Single-threaded
        # by design — this handler loop is the only caller.
        from ray_tpu.observability.traces import TraceStore

        self.trace_store = TraceStore(
            maxlen=GlobalConfig.trace_store_maxlen,
            keep_threshold_s=GlobalConfig.trace_keep_threshold_s,
            sample_rate=GlobalConfig.trace_sample_rate,
            pending_max=GlobalConfig.trace_pending_max)

        # Control-plane decision ring: every autoscale / backpressure /
        # preemption action with the metric reading that triggered it,
        # so "why did it scale?" is answerable from the dashboard
        # (GET /api/controller) without scraping logs.
        self.ctrl_decisions: deque = deque(
            maxlen=GlobalConfig.ctrl_decisions_buffer_size)
        self._ctrl_decision_seq = 0

        # Cluster-wide prefix index: replica -> its newest published
        # set of KV hash-chain heads (stable_hash, depth) + tier
        # residency summary. A routing HINT, not a directory: entries
        # expire after serve_prefix_index_ttl_s without a re-publish,
        # and every consumer re-verifies against real tokens before
        # trusting a hash (serve/llm/kv_cache.stable_hash_prefix).
        self.prefix_index: Dict[str, Dict[str, Any]] = {}

        # Cross-worker train step matrix: every instrumented train /
        # learner worker publishes one row per step (worker, step,
        # wall_s, per-phase seconds, goodput snapshot). The row doubles
        # as the worker's step heartbeat: the straggler detector runs
        # on ingest, the stall watchdog ages the per-worker last-report
        # timestamps and auto-captures stacks from workers that go
        # quiet mid-run.
        self.train_steps: deque = deque(
            maxlen=GlobalConfig.train_steps_buffer_size)
        self._train_step_seq = 0
        self.train_workers: Dict[str, Dict[str, Any]] = {}
        self._train_straggler = None  # lazy StragglerDetector
        self._train_stragglers: deque = deque(maxlen=64)
        self._train_watchdog_task = None

        # Serve cost-accounting ring (observability/accounting.py):
        # every finished serve request publishes one cost row (tenant,
        # lane, trace_id, tokens, block/chip-seconds). Same shape as
        # the train-step ring — bounded deque + monotone seq, windowed
        # aggregation server-side: the bounded TenantLedger folds rows
        # on ingest and the per-lane SLOTracker evaluates TTFT/TPOT
        # attainment, recording SLO_BURN when both burn windows trip.
        self.serve_accounting: deque = deque(
            maxlen=GlobalConfig.serve_accounting_buffer_size)
        self._serve_acct_seq = 0
        self._serve_ledger = None   # lazy accounting.TenantLedger
        self._serve_slo = None      # lazy accounting.SLOTracker

        # XLA program-attribution ring (observability/xla.py): every
        # tracked_jit publishes its compiled programs' cost rows here
        # (flops, HBM bytes, sampled MFU/MBU, roofline verdict). The
        # ring keeps row history; ``xla_latest`` keeps only each
        # program's newest row — the fleet's current program set that
        # the summary ranks by FLOPs, HBM, and lost-to-roofline
        # headroom.
        self.xla_programs: deque = deque(
            maxlen=GlobalConfig.xla_programs_buffer_size)
        self._xla_seq = 0
        self.xla_latest: Dict[tuple, Dict[str, Any]] = {}

        self._reschedule_on_start: List[bytes] = []
        self._register_handlers()
        # Actor/PG lifecycle transitions all publish; piggyback snapshot
        # dirty-marking there so bounce recovery stays fresh.
        self.pubsub.on_publish = self._on_publish
        self._health_task = None
        self._snapshot_path: Optional[str] = None
        self._snapshot_task = None
        self._snapshot_dirty = False
        self._snapshot_errors = 0

    # ------------------------------------------------------------------ boot
    def start(self) -> int:
        port = self.server.start()
        self._health_task = get_io_loop().submit(self._health_loop())
        self._train_watchdog_task = get_io_loop().submit(
            self._train_watchdog_loop())
        for actor_id in self._reschedule_on_start:
            get_io_loop().submit(self._schedule_actor(actor_id))
        self._reschedule_on_start = []
        if self._snapshot_path:
            self._snapshot_task = get_io_loop().submit(self._snapshot_loop())
        return port

    def _on_publish(self, channel: str) -> None:
        if channel in ("actor", "pg"):
            self._snapshot_dirty = True

    # ------------------------------------------------------- persistence
    def enable_snapshots(self, path: str) -> None:
        """Persist the durable tables (KV, jobs, named actors) to `path`
        periodically; load an existing snapshot now. Runtime state (nodes,
        leases, live actors) intentionally rebuilds via re-registration."""
        import pickle

        self._snapshot_path = path
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    snap = pickle.load(f)
                for ns, entries in snap.get("kv", {}).items():
                    self.kv[ns].update(entries)
                self.jobs.update(snap.get("jobs", {}))
                self._next_job_int = max(self._next_job_int,
                                         snap.get("next_job_int", 0))
                # Live-actor and PG tables survive a control-plane bounce
                # (reference: redis-backed gcs_actor_table): addresses may
                # be stale; death reports and failed pushes correct them.
                for actor_id, rec in snap.get("actors", {}).items():
                    rec = dict(rec)
                    self.actors[actor_id] = rec
                    self._actor_events[actor_id] = asyncio.Event()
                    if rec.get("state") == ALIVE:
                        self._actor_events[actor_id].set()
                    elif rec.get("state") in (PENDING_CREATION, RESTARTING):
                        # Their scheduling coroutine died with the old
                        # process; restart it once the loop is up.
                        self._reschedule_on_start.append(actor_id)
                self.named_actors.update(snap.get("named_actors", {}))
                self.placement_groups.update(snap.get("pgs", {}))
            except Exception as e:  # corrupt snapshot: recover empty, SAY SO
                import sys

                print(f"[gcs] WARNING: snapshot at {path} unreadable "
                      f"({type(e).__name__}: {e}); starting without "
                      "recovered state", file=sys.stderr, flush=True)

    def _build_snapshot(self) -> dict:
        """Consistent one-level-deep copies, taken ON the event loop so
        handler mutations can't race the pickle (observed under a
        500-actor storm: 'dictionary changed size during iteration'
        from the executor thread)."""
        actors = {}
        for aid, rec in list(self.actors.items()):
            actors[aid] = {k: v for k, v in rec.items() if k != "handle"}
        return {
            "kv": {ns: dict(entries)
                   for ns, entries in self.kv.items()},
            "jobs": {k: dict(v) if isinstance(v, dict) else v
                     for k, v in self.jobs.items()},
            "next_job_int": self._next_job_int,
            "actors": actors,
            "named_actors": dict(self.named_actors),
            "pgs": {k: dict(v) if isinstance(v, dict) else v
                    for k, v in self.placement_groups.items()},
        }

    def _write_snapshot(self, snap: dict) -> None:
        import pickle

        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(snap, f)
        os.replace(tmp, self._snapshot_path)

    async def _snapshot_loop(self):
        import sys

        while True:
            await asyncio.sleep(5.0)
            if not self._snapshot_dirty:
                continue
            self._snapshot_dirty = False
            try:
                # Copies on-loop (consistent), pickle+write off-loop: a
                # large KV (exported functions) must not stall
                # heartbeat handling.
                snap = self._build_snapshot()
                await asyncio.get_running_loop().run_in_executor(
                    None, self._write_snapshot, snap)
                self._snapshot_errors = 0
            except Exception as e:
                self._snapshot_dirty = True
                self._snapshot_errors += 1
                if self._snapshot_errors in (1, 10, 100):
                    print(f"[gcs] WARNING: snapshot write failed x"
                          f"{self._snapshot_errors} "
                          f"({type(e).__name__}: {e})",
                          file=sys.stderr, flush=True)

    def _register_handlers(self):
        s = self.server
        for name in [
            "register_node", "heartbeat", "get_all_nodes", "drain_node",
            "kv_put", "kv_get", "kv_del", "kv_keys", "kv_exists",
            "register_actor", "register_actors", "get_actor_info",
            "get_named_actor",
            "list_named_actors", "kill_actor", "gc_actor",
            "report_actor_death",
            "wait_actor_ready", "list_actors",
            "create_placement_group", "remove_placement_group",
            "get_placement_group", "wait_placement_group_ready",
            "list_placement_groups",
            "next_job_id", "register_job", "mark_job_finished", "list_jobs",
            "get_job_info",
            "publish", "poll", "pubsub_seq", "push_task_events",
            "get_task_events",
            "register_worker", "list_workers", "get_system_config",
            "cluster_resources", "available_resources", "internal_stats",
            "metrics_text", "get_cluster_load", "push_metrics",
            "user_metrics_summary",
            "report_cluster_event", "list_cluster_events",
            "summary_cluster_events",
            "report_ctrl_decision", "list_ctrl_decisions",
            "report_prefix_index", "lookup_prefix_index",
            "report_train_steps", "list_train_steps", "train_summary",
            "report_serve_accounting", "list_serve_accounting",
            "serve_accounting_summary",
            "report_xla_programs", "list_xla_programs", "xla_summary",
            "get_trace", "list_traces", "trace_stats",
        ]:
            s.register(name, getattr(self, f"_h_{name}"))

    # --------------------------------------------------------- cluster events
    def _record_event(self, event_type: str, message: str,
                      severity: Optional[str] = None,
                      node_id: Optional[str] = None, **extra) -> None:
        """Append one typed event to the ClusterEventLog. ERROR-severity
        events are additionally broadcast on the "logs" pubsub channel
        so every driver echoes them (reference: error-message pubsub)."""
        from ray_tpu.observability import events as _events

        try:
            event = _events.make_event(event_type, message,
                                       severity=severity,
                                       node_id=node_id, **extra)
        except ValueError as e:
            print(f"[gcs] WARNING: dropping malformed cluster event: {e}",
                  file=sys.stderr, flush=True)
            return
        self._event_seq += 1
        event["seq"] = self._event_seq
        self.cluster_events.append(event)
        self._event_counts[(event["type"], event["severity"])] += 1
        if event["severity"] == "ERROR":
            self.pubsub.publish("logs", {"cluster_event": event})

    async def _h_report_cluster_event(self, event_type, message,
                                      severity=None, node_id=None,
                                      extra=None):
        self._record_event(event_type, message, severity=severity,
                           node_id=node_id, **(extra or {}))
        return True

    async def _h_list_cluster_events(self, event_type=None, severity=None,
                                     node_id=None, limit=100):
        """Newest-last slice of the event ring, optionally filtered by
        type, severity, and node-id hex prefix."""
        out = []
        for e in self.cluster_events:
            if event_type is not None and e["type"] != event_type:
                continue
            if severity is not None and e["severity"] != severity:
                continue
            if node_id is not None and not (
                    e.get("node_id") or "").startswith(node_id):
                continue
            out.append(e)
        return out[-max(int(limit), 0):]

    async def _h_summary_cluster_events(self):
        """Rollup by (type, severity) over everything ever recorded —
        counts are monotone, unlike the bounded ring itself."""
        by_type: Dict[str, Dict[str, int]] = defaultdict(dict)
        for (etype, sev), n in self._event_counts.items():
            by_type[etype][sev] = n
        return {"total_recorded": self._event_seq,
                "in_buffer": len(self.cluster_events),
                "by_type": {t: dict(v) for t, v in by_type.items()}}

    # ------------------------------------------------- control-plane decisions
    async def _h_report_ctrl_decision(self, controller: str, action: str,
                                      reason: str = "", reading=None,
                                      node_id=None):
        """One control-plane decision (autoscale, backpressure adjust,
        memory preemption) with the metric reading that triggered it."""
        self._ctrl_decision_seq += 1
        self.ctrl_decisions.append({
            "seq": self._ctrl_decision_seq, "ts": time.time(),
            "controller": str(controller), "action": str(action),
            "reason": str(reason), "reading": dict(reading or {}),
            "node_id": node_id,
        })
        return True

    async def _h_list_ctrl_decisions(self, controller=None, action=None,
                                     limit=100):
        """Newest-last slice of the decision ring, optionally filtered."""
        out = []
        for d in self.ctrl_decisions:
            if controller is not None and d["controller"] != controller:
                continue
            if action is not None and d["action"] != action:
                continue
            out.append(d)
        return out[-max(int(limit), 0):]

    # ------------------------------------------------- cluster prefix index
    async def _h_report_prefix_index(self, replica, heads, tiers=None):
        """One LLM replica's cache-aware-routing hint: the hash-chain
        heads it can serve without prefilling (hottest first, capped at
        serve_prefix_index_max_heads) plus a tier residency summary.
        Last write wins per replica; the report IS the heartbeat — a
        replica that stops publishing ages out at lookup."""
        cap = int(GlobalConfig.serve_prefix_index_max_heads)
        self.prefix_index[str(replica)] = {
            "heads": [(int(h), int(d)) for h, d in list(heads)[:cap]],
            "tiers": dict(tiers or {}),
            "ts": time.time(),
        }
        return True

    async def _h_lookup_prefix_index(self):
        """TTL-filtered snapshot: {replica: {heads, tiers, age_s}}.
        Expired entries are dropped here (lazy expiry — no sweeper
        task to keep alive across bounces)."""
        ttl = float(GlobalConfig.serve_prefix_index_ttl_s)
        now = time.time()
        out: Dict[str, Any] = {}
        for rep in list(self.prefix_index):
            rec = self.prefix_index[rep]
            age = now - rec["ts"]
            if age > ttl:
                del self.prefix_index[rep]
                continue
            out[rep] = {"heads": list(rec["heads"]),
                        "tiers": dict(rec["tiers"]),
                        "age_s": age}
        return out

    # --------------------------------------------------- train step matrix
    def _train_detector(self):
        if self._train_straggler is None:
            from ray_tpu.observability.goodput import StragglerDetector

            self._train_straggler = StragglerDetector(
                threshold=float(GlobalConfig.train_straggler_threshold),
                window=int(GlobalConfig.train_straggler_window))
        return self._train_straggler

    async def _h_report_train_steps(self, row=None, rows=None):
        """Train/learner workers publish step rows here (worker, step,
        wall_s, phases{phase: seconds}, optional goodput snapshot). One
        row per step, batched via `rows` when a worker catches up. The
        report IS the worker's heartbeat — the stall watchdog ages
        these, and a row with ``done: true`` marks the worker idle so a
        finished run never trips it. The straggler detector runs on
        ingest and records TRAIN_STRAGGLER naming the dominant phase."""
        for r in list(rows or []) + ([row] if row else []):
            try:
                self._ingest_train_row(dict(r))
            except Exception as e:
                print(f"[gcs] WARNING: dropping malformed train step row: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
        return True

    def _ingest_train_row(self, row: dict) -> None:
        worker = str(row.get("worker") or "")
        if not worker:
            return
        info = self.train_workers.setdefault(worker, {
            "worker": worker, "walls": deque(maxlen=32), "steps": 0,
            "last_step": None, "stalled": False, "done": False,
            "straggler": None, "goodput": None,
        })
        info["last_ts"] = time.monotonic()
        for key in ("worker_id", "node_id"):
            if row.get(key) is not None:
                info[key] = row[key]
        if row.get("done"):
            info["done"] = True
            info["stalled"] = False
            if isinstance(row.get("goodput"), dict):
                info["goodput"] = dict(row["goodput"])
            return
        # Any real step row revives a worker previously marked done or
        # stalled (next kick / recovered hang).
        info["done"] = False
        info["stalled"] = False
        rec = {
            "worker": worker,
            "step": int(row.get("step", 0)),
            "wall_s": float(row.get("wall_s", 0.0)),
            "phases": {str(k): float(v)
                       for k, v in dict(row.get("phases") or {}).items()},
            "recv_ts": time.time(),
        }
        if isinstance(row.get("goodput"), dict):
            rec["goodput"] = dict(row["goodput"])
            info["goodput"] = rec["goodput"]
        self._train_step_seq += 1
        rec["seq"] = self._train_step_seq
        self.train_steps.append(rec)
        info["steps"] += 1
        info["last_step"] = rec["step"]
        info["walls"].append(rec["wall_s"])
        flag = self._train_detector().observe(
            worker, rec["step"], rec["wall_s"], rec["phases"])
        if flag:
            info["straggler"] = flag
            self._train_stragglers.append(dict(flag, ts=time.time()))
            node_id = info.get("node_id")
            self._record_event(
                "TRAIN_STRAGGLER",
                f"train worker {worker} is a straggler: mean step "
                f"{flag['mean_step_s']:.3f}s vs pod median "
                f"{flag['median_step_s']:.3f}s ({flag['ratio']:.2f}x); "
                f"dominant phase {flag['dominant_phase']} "
                f"(+{flag['dominant_excess_s']:.3f}s over peers)",
                node_id=node_id.hex() if hasattr(node_id, "hex")
                else node_id,
                worker=worker, step=flag["step"],
                ratio=round(float(flag["ratio"]), 3),
                dominant_phase=flag["dominant_phase"],
                dominant_excess_s=round(
                    float(flag["dominant_excess_s"]), 4),
                mean_step_s=round(float(flag["mean_step_s"]), 4),
                median_step_s=round(float(flag["median_step_s"]), 4))

    async def _h_list_train_steps(self, worker=None, limit=200):
        """Newest-last slice of the step-row ring, optionally filtered
        by worker label."""
        out = []
        for rec in self.train_steps:
            if worker is not None and rec["worker"] != worker:
                continue
            out.append(rec)
        return out[-max(int(limit), 0):]

    async def _h_train_summary(self):
        """The cross-worker rollup behind `util.state.train_summary()`
        and `GET /api/train`: per-worker step stats + stall/straggler
        flags, the cluster goodput ratio (productive seconds over
        accounted seconds, weighted by each worker's ledger), lost
        seconds by cause, and per-phase means over the buffered rows."""
        now = time.monotonic()
        phase_tot: Dict[str, float] = defaultdict(float)
        phase_n: Dict[str, int] = defaultdict(int)
        for rec in self.train_steps:
            for ph, s in rec["phases"].items():
                phase_tot[ph] += s
                phase_n[ph] += 1
        workers = []
        tot_prod = tot_acc = 0.0
        lost: Dict[str, float] = defaultdict(float)
        for w in sorted(self.train_workers):
            info = self.train_workers[w]
            walls = [s for s in info["walls"]]
            node_id = info.get("node_id")
            row = {
                "worker": w,
                "steps": info["steps"],
                "last_step": info["last_step"],
                "age_s": round(now - info.get("last_ts", now), 3),
                "mean_step_s": (sum(walls) / len(walls)) if walls else None,
                "stalled": info["stalled"],
                "done": info["done"],
                "straggler": info.get("straggler"),
                "node_id": node_id.hex() if hasattr(node_id, "hex")
                           else node_id,
            }
            g = info.get("goodput")
            if g:
                row["goodput_ratio"] = g.get("goodput_ratio")
                tot_prod += float(g.get("productive_s") or 0.0)
                tot_acc += float(g.get("accounted_s") or 0.0)
                for cause, s in dict(g.get("lost_s") or {}).items():
                    lost[cause] += float(s)
            workers.append(row)
        return {
            "workers": workers,
            "steps_in_buffer": len(self.train_steps),
            "steps_recorded": self._train_step_seq,
            "goodput_ratio": (tot_prod / tot_acc) if tot_acc else None,
            "productive_s": tot_prod,
            "accounted_s": tot_acc,
            "lost_seconds": dict(lost),
            "phase_mean_s": {ph: phase_tot[ph] / phase_n[ph]
                             for ph in phase_tot},
            "stragglers": list(self._train_stragglers),
            "stalled": [r["worker"] for r in workers if r["stalled"]],
        }

    # ------------------------------------------------- serve accounting
    def _serve_acct_ledger(self):
        if self._serve_ledger is None:
            from ray_tpu.observability.accounting import TenantLedger

            self._serve_ledger = TenantLedger(
                max_tenants=int(
                    GlobalConfig.serve_accounting_max_tenants))
        return self._serve_ledger

    def _serve_slo_tracker(self):
        if self._serve_slo is None:
            from ray_tpu.observability.accounting import SLOTracker

            self._serve_slo = SLOTracker()
        return self._serve_slo

    async def _h_report_serve_accounting(self, row=None, rows=None):
        """Serve engines publish one cost row per finished request
        (RequestMeter.finalize shape), batched via ``rows`` when a
        replica catches up. Ingest folds the bounded tenant ledger and
        runs the SLO burn evaluation — the row is both the billing
        record and the lane's attainment sample."""
        for r in list(rows or []) + ([row] if row else []):
            try:
                self._ingest_serve_row(dict(r))
            except Exception as e:
                print(f"[gcs] WARNING: dropping malformed serve "
                      f"accounting row: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
        return True

    def _ingest_serve_row(self, row: dict) -> None:
        def _opt(key):
            v = row.get(key)
            return None if v is None else float(v)

        # Rows cross process boundaries and land on JSON surfaces
        # (/api/accounting) — a raw-bytes node id must become hex here.
        node_id = row.get("node_id")
        rec = {
            "tenant": str(row.get("tenant") or "default"),
            "model": str(row.get("model") or ""),
            "lane": str(row.get("lane") or "interactive"),
            "trace_id": row.get("trace_id"),
            "request_id": row.get("request_id"),
            "node_id": (node_id.hex() if hasattr(node_id, "hex")
                        else node_id),
            "tokens_out": int(row.get("tokens_out") or 0),
            "prefill_tokens_computed": int(
                row.get("prefill_tokens_computed") or 0),
            "prefill_tokens_avoided": int(
                row.get("prefill_tokens_avoided") or 0),
            "spec_proposed": int(row.get("spec_proposed") or 0),
            "spec_accepted": int(row.get("spec_accepted") or 0),
            "block_seconds": float(row.get("block_seconds") or 0.0),
            "chip_seconds": {
                str(k): float(v) for k, v in
                dict(row.get("chip_seconds") or {}).items()},
            "chip_seconds_total": float(
                row.get("chip_seconds_total") or 0.0),
            "migrations": int(row.get("migrations") or 0),
            "queue_wait_s": _opt("queue_wait_s"),
            "ttft_s": _opt("ttft_s"),
            "tpot_s": _opt("tpot_s"),
            "e2e_s": _opt("e2e_s"),
            "finish_reason": row.get("finish_reason"),
            "recv_ts": time.time(),
        }
        self._serve_acct_seq += 1
        rec["seq"] = self._serve_acct_seq
        self.serve_accounting.append(rec)
        self._serve_acct_ledger().fold(rec)
        # Only rows with a measured first token are SLO samples — a
        # cancelled-in-queue request has no latency to attain.
        if rec["ttft_s"] is None:
            return
        flag = self._serve_slo_tracker().observe(
            rec["lane"], rec["ttft_s"], rec["tpot_s"])
        if flag:
            self._record_event(
                "SLO_BURN",
                f"serve lane {flag['lane']} is burning its SLO error "
                f"budget: fast burn {flag['fast_burn']}x over "
                f"{flag['window_fast_s']:.0f}s (attainment "
                f"{flag['attainment_fast']:.4f} vs objective "
                f"{flag['objective']}), slow burn {flag['slow_burn']}x "
                f"over {flag['window_slow_s']:.0f}s; targets "
                f"ttft<={flag['ttft_target_s']}s "
                f"tpot<={flag['tpot_target_s']}s",
                lane=flag["lane"],
                fast_burn=flag["fast_burn"],
                slow_burn=flag["slow_burn"],
                attainment_fast=flag["attainment_fast"],
                attainment_slow=flag["attainment_slow"],
                objective=flag["objective"],
                ttft_target_s=flag["ttft_target_s"],
                tpot_target_s=flag["tpot_target_s"])

    async def _h_list_serve_accounting(self, tenant=None, lane=None,
                                       trace_id=None, limit=200):
        """Newest-last slice of the cost-row ring, optionally filtered
        by tenant, lane, or exact trace id (the ``x-trace-id`` a routed
        request returned)."""
        out = []
        for rec in self.serve_accounting:
            if tenant is not None and rec["tenant"] != tenant:
                continue
            if lane is not None and rec["lane"] != lane:
                continue
            if trace_id is not None and rec["trace_id"] != trace_id:
                continue
            out.append(rec)
        return out[-max(int(limit), 0):]

    async def _h_serve_accounting_summary(self, top_n=None,
                                          trace_id=None):
        """The rollup behind ``util.state.serve_accounting()`` and
        ``GET /api/accounting``: top-N tenants by chip-seconds (the
        "which tenant is eating the fleet?" answer), per-lane SLO
        attainment/burn, ring occupancy — plus, given ``trace_id``,
        that request's own cost row."""
        if top_n is None:
            top_n = int(GlobalConfig.serve_accounting_top_n)
        ledger = self._serve_acct_ledger()
        out = {
            "tenants": ledger.top(int(top_n)),
            "tenants_tracked": len(ledger),
            "rows_in_buffer": len(self.serve_accounting),
            "rows_recorded": self._serve_acct_seq,
            "slo": self._serve_slo_tracker().snapshot(),
        }
        if trace_id is not None:
            out["request"] = next(
                (rec for rec in reversed(self.serve_accounting)
                 if rec["trace_id"] == trace_id), None)
        return out

    # ---------------------------------------------- xla program costs
    _XLA_FLOAT_FIELDS = (
        "flops", "bytes_accessed", "transcendentals", "arg_bytes",
        "out_bytes", "temp_bytes", "alias_bytes", "peak_hbm_bytes",
        "compile_seconds", "wall_s", "achieved_flops_per_s",
        "achieved_bytes_per_s", "mfu", "mbu", "exposed_comm_fraction",
        "lost_roofline_s_per_call", "lost_roofline_s_total")

    async def _h_report_xla_programs(self, row=None, rows=None):
        """Tracked-jit processes publish program cost rows here: one on
        every compile (cost/memory analysis) and one per sampled wall
        (MFU/MBU + verdict refresh). Batched via ``rows`` when a
        publisher catches up."""
        for r in list(rows or []) + ([row] if row else []):
            try:
                self._ingest_xla_row(dict(r))
            except Exception as e:
                print(f"[gcs] WARNING: dropping malformed xla program "
                      f"row: {type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
        return True

    def _ingest_xla_row(self, row: dict) -> None:
        fn = str(row.get("fn") or "")
        signature = str(row.get("signature") or "")
        if not fn or not signature:
            raise ValueError("xla program row needs fn and signature")
        rec: Dict[str, Any] = {"fn": fn, "signature": signature}
        for key in self._XLA_FLOAT_FIELDS:
            v = row.get(key)
            rec[key] = None if v is None else float(v)
        rec["calls"] = int(row.get("calls") or 0)
        rec["samples"] = int(row.get("samples") or 0)
        rec["verdict"] = str(row.get("verdict") or "unsampled")
        rec["spec"] = str(row.get("spec") or "unknown")
        rec["measurement"] = str(row.get("measurement") or "unknown")
        rec["pid"] = int(row.get("pid") or 0)
        node_id = row.get("node_id")
        rec["node_id"] = node_id.hex() if hasattr(node_id, "hex") \
            else node_id
        rec["recv_ts"] = time.time()
        self._xla_seq += 1
        rec["seq"] = self._xla_seq
        self.xla_programs.append(rec)
        self.xla_latest[(rec["node_id"], rec["pid"], fn, signature)] = rec
        # The latest-view is bounded by the same knob as the ring:
        # evict the stalest program when a churning fleet overflows it.
        while len(self.xla_latest) > (self.xla_programs.maxlen or 0) > 0:
            oldest = min(self.xla_latest,
                         key=lambda k: self.xla_latest[k]["seq"])
            del self.xla_latest[oldest]

    async def _h_list_xla_programs(self, fn=None, verdict=None,
                                   limit=200):
        """Newest-last slice of the program-row ring, optionally
        filtered by function name or roofline verdict."""
        out = []
        for rec in self.xla_programs:
            if fn is not None and rec["fn"] != fn:
                continue
            if verdict is not None and rec["verdict"] != verdict:
                continue
            out.append(rec)
        return out[-max(int(limit), 0):]

    async def _h_xla_summary(self, top_n=8):
        """The rollup behind ``util.state.xla_summary()`` and
        ``GET /api/programs``: the fleet's current program set ranked
        by cumulative FLOPs, peak HBM bytes, and lost-to-roofline
        headroom seconds, plus verdict/measurement counts (an all-cpu
        ``measurements`` dict says the ratios prove plumbing, not
        performance)."""
        top_n = max(int(top_n), 1)
        rows = list(self.xla_latest.values())

        def total_flops(r):
            return (r["flops"] or 0.0) * max(r["calls"], 1)

        sampled = [r for r in rows
                   if r.get("lost_roofline_s_total") is not None]
        verdicts: Dict[str, int] = defaultdict(int)
        measurements: Dict[str, int] = defaultdict(int)
        for r in rows:
            verdicts[r["verdict"]] += 1
            measurements[r["measurement"]] += 1
        return {
            "programs": len(rows),
            "rows_in_buffer": len(self.xla_programs),
            "rows_recorded": self._xla_seq,
            "total_flops": sum(total_flops(r) for r in rows),
            "total_peak_hbm_bytes": sum(
                r["peak_hbm_bytes"] or 0.0 for r in rows),
            "lost_roofline_s_total": sum(
                r["lost_roofline_s_total"] for r in sampled),
            "verdicts": dict(verdicts),
            "measurements": dict(measurements),
            "top_by_flops": sorted(
                rows, key=total_flops, reverse=True)[:top_n],
            "top_by_hbm": sorted(
                rows, key=lambda r: r["peak_hbm_bytes"] or 0.0,
                reverse=True)[:top_n],
            "top_by_headroom": sorted(
                sampled, key=lambda r: r["lost_roofline_s_total"],
                reverse=True)[:top_n],
        }

    async def _train_watchdog_loop(self):
        """Stall watchdog: a worker that published step rows and then
        went quiet for longer than `train_stall_heartbeats` times its
        own median step wall (floored at `train_stall_min_timeout_s`)
        is marked stalled and a TRAIN_STALL event is recorded WITH the
        worker's thread stacks auto-captured via its raylet's
        dump_stacks — the forensics arrive with the page, not after
        someone ssh'es in. Workers that reported ``done`` are exempt
        until their next row."""
        from statistics import median

        while True:
            await asyncio.sleep(
                float(GlobalConfig.train_stall_check_interval_s))
            if not self.train_workers:
                continue
            beats = int(GlobalConfig.train_stall_heartbeats)
            floor = float(GlobalConfig.train_stall_min_timeout_s)
            now = time.monotonic()
            for w, info in list(self.train_workers.items()):
                if info.get("done") or info.get("stalled"):
                    continue
                if not info["steps"]:
                    continue
                walls = [s for s in info["walls"] if s > 0]
                timeout = max(floor,
                              beats * (median(walls) if walls else 0.0))
                age = now - info.get("last_ts", now)
                if age <= timeout:
                    continue
                info["stalled"] = True
                stacks = await self._capture_train_stacks(info)
                node_id = info.get("node_id")
                self._record_event(
                    "TRAIN_STALL",
                    f"train worker {w} stalled: no step report for "
                    f"{age:.1f}s (timeout {timeout:.1f}s after "
                    f"{info['steps']} steps); thread stacks "
                    + ("attached" if stacks else "unavailable"),
                    node_id=node_id.hex() if hasattr(node_id, "hex")
                    else node_id,
                    worker=w, age_s=round(age, 3),
                    timeout_s=round(timeout, 3),
                    last_step=info["last_step"],
                    stacks=stacks)

    async def _capture_train_stacks(self, info: dict):
        """Best-effort dump_stacks against the stalled worker's raylet;
        returns formatted stack text (truncated) or None. Never raises —
        forensics failing must not take the watchdog down with it."""
        node_id = info.get("node_id")
        if node_id is None:
            return None
        client = self._client_for_node(node_id)
        if client is None:
            return None
        try:
            reply = await client.acall(
                "dump_stacks", worker_id=info.get("worker_id"),
                timeout=15)
        except Exception:
            return None
        texts = []
        for whex, rec in (reply or {}).items():
            if isinstance(rec, dict) and rec.get("stacks"):
                texts.append(f"worker {whex[:12]}:\n{rec['stacks']}")
        return "\n\n".join(texts)[:20000] or None

    # --------------------------------------------------------------- metrics
    async def _h_metrics_text(self) -> str:
        """Cluster metrics in Prometheus exposition format (reference:
        `stats/metric_defs.h` + MetricsAgent -> Prometheus scrape)."""
        # Naming discipline (linted by scripts/check_metrics.py): the
        # `_total` suffix is reserved for counters; state-breakdown
        # gauges export without it.
        lines = [
            "# HELP rtpu_nodes Nodes by liveness state.",
            "# TYPE rtpu_nodes gauge",
        ]
        by_state: Dict[str, int] = defaultdict(int)
        for info in self.nodes.values():
            by_state[info["state"]] += 1
        for state, n in by_state.items():
            lines.append(f'rtpu_nodes{{state="{state}"}} {n}')

        lines += ["# HELP rtpu_actors Actors by lifecycle state.",
                  "# TYPE rtpu_actors gauge"]
        actor_states: Dict[str, int] = defaultdict(int)
        for a in self.actors.values():
            actor_states[a.get("state", "UNKNOWN")] += 1
        for state, n in actor_states.items():
            lines.append(f'rtpu_actors{{state="{state}"}} {n}')

        # Counter semantics: monotone totals of everything ever pushed,
        # NOT a scan of the ring buffer (which shrinks as entries age
        # out and would make rate() see phantom resets).
        lines += ["# HELP rtpu_tasks_events_total Task lifecycle events "
                  "recorded since GCS start.",
                  "# TYPE rtpu_tasks_events_total counter"]
        for state, n in self._task_event_counts.items():
            lines.append(f'rtpu_tasks_events_total{{state="{state}"}} {n}')

        lines += ["# HELP rtpu_cluster_events_total Cluster events "
                  "recorded since GCS start, by type and severity.",
                  "# TYPE rtpu_cluster_events_total counter"]
        for (etype, sev), n in self._event_counts.items():
            lines.append(
                f'rtpu_cluster_events_total{{type="{etype}",'
                f'severity="{sev}"}} {n}')

        lines += ["# HELP rtpu_resource_capacity Cluster resource "
                  "capacity.",
                  "# TYPE rtpu_resource_capacity gauge",
                  "# HELP rtpu_resource_available Cluster resource "
                  "availability.",
                  "# TYPE rtpu_resource_available gauge"]
        for snap in self._nodes_snapshot():
            if snap["state"] != ALIVE:
                continue
            nid = snap["node_id"].hex()[:12]
            for key, val in snap["total"].items():
                lines.append(
                    f'rtpu_resource_capacity{{node="{nid}",'
                    f'resource="{key}"}} {val}')
            for key, val in snap["available"].items():
                lines.append(
                    f'rtpu_resource_available{{node="{nid}",'
                    f'resource="{key}"}} {val}')

        lines += ["# HELP rtpu_placement_groups Placement groups by "
                  "state.",
                  "# TYPE rtpu_placement_groups gauge"]
        pg_states: Dict[str, int] = defaultdict(int)
        for pg in self.placement_groups.values():
            pg_states[pg.get("state", "UNKNOWN")] += 1
        for state, n in pg_states.items():
            lines.append(
                f'rtpu_placement_groups{{state="{state}"}} {n}')

        # Tail-sampled trace store health: monotone totals from
        # TraceStore.stats() plus the two occupancy gauges.
        ts = self.trace_store.stats()
        lines += ["# HELP rtpu_trace_kept_total Completed traces kept "
                  "by tail-sampling, by reason.",
                  "# TYPE rtpu_trace_kept_total counter",
                  f"rtpu_trace_kept_total {ts['kept']}",
                  "# HELP rtpu_trace_sampled_out_total Completed fast, "
                  "clean traces dropped by trace_sample_rate.",
                  "# TYPE rtpu_trace_sampled_out_total counter",
                  f"rtpu_trace_sampled_out_total {ts['sampled_out']}",
                  "# HELP rtpu_trace_evicted_pending_total Rootless "
                  "in-flight traces evicted at trace_pending_max.",
                  "# TYPE rtpu_trace_evicted_pending_total counter",
                  f"rtpu_trace_evicted_pending_total "
                  f"{ts['evicted_pending']}",
                  "# HELP rtpu_trace_evicted_kept_total Kept traces "
                  "aged out of the trace_store_maxlen LRU ring.",
                  "# TYPE rtpu_trace_evicted_kept_total counter",
                  f"rtpu_trace_evicted_kept_total {ts['evicted_kept']}",
                  "# HELP rtpu_trace_spans_seen_total Trace-tagged SPAN "
                  "events routed into the trace store.",
                  "# TYPE rtpu_trace_spans_seen_total counter",
                  f"rtpu_trace_spans_seen_total {ts['spans_seen']}",
                  "# HELP rtpu_trace_spans_dropped_total Spans dropped "
                  "at the per-trace span cap.",
                  "# TYPE rtpu_trace_spans_dropped_total counter",
                  f"rtpu_trace_spans_dropped_total {ts['spans_dropped']}",
                  "# HELP rtpu_trace_pending In-flight (rootless) "
                  "traces accumulating in the store.",
                  "# TYPE rtpu_trace_pending gauge",
                  f"rtpu_trace_pending {ts['pending']}",
                  "# HELP rtpu_trace_stored Kept traces currently "
                  "retrievable from the store.",
                  "# TYPE rtpu_trace_stored gauge",
                  f"rtpu_trace_stored {ts['stored']}"]
        # Serve SLO attainment/burn: the SLOTracker lives in THIS
        # process (evaluated on accounting-row ingest), so its gauges
        # export natively here rather than through the push path.
        if self._serve_slo is not None:
            slo = self._serve_slo.snapshot()
            if slo:
                lines += ["# HELP rtpu_serve_slo_attainment_ratio "
                          "Fraction of requests in the fast window "
                          "meeting the lane's TTFT/TPOT targets.",
                          "# TYPE rtpu_serve_slo_attainment_ratio gauge",
                          "# HELP rtpu_serve_slo_burn_rate SLO "
                          "error-budget burn rate per lane and window; "
                          "1.0 consumes budget exactly at the "
                          "objective's allowance.",
                          "# TYPE rtpu_serve_slo_burn_rate gauge"]
                for lane, ent in sorted(slo.items()):
                    if ent.get("attainment_fast") is not None:
                        lines.append(
                            f'rtpu_serve_slo_attainment_ratio'
                            f'{{lane="{lane}"}} '
                            f'{ent["attainment_fast"]}')
                    for window in ("fast", "slow"):
                        burn = ent.get(f"burn_{window}")
                        if burn is not None:
                            lines.append(
                                f'rtpu_serve_slo_burn_rate'
                                f'{{lane="{lane}",window="{window}"}} '
                                f'{burn}')
        lines.extend(self._render_user_metrics())
        return "\n".join(lines) + "\n"

    async def _h_push_metrics(self, source: str, records):
        self.user_metrics[source] = (time.time(), records)
        return True

    async def _h_user_metrics_summary(self, prefixes=None):
        """Aggregated user metrics as plain dicts (dashboard /api/serve).
        ``prefixes``: optional list of metric-name prefixes to keep."""
        metas, counters, gauges, hists, fresh, exemplars = \
            self._aggregate_user_metrics()
        now = time.time()
        out: Dict[str, Any] = {}
        for name, meta in metas.items():
            if prefixes and not any(name.startswith(p) for p in prefixes):
                continue
            typ = meta["type"]
            entry: Dict[str, Any] = {
                "type": typ, "description": meta.get("description", ""),
                # Age of the freshest live push carrying this metric —
                # the MetricsHub staleness signal. None means only
                # tombstones of exited sources remain.
                "age_s": (max(0.0, now - fresh[name])
                          if name in fresh else None)}
            if typ == "counter":
                entry["data"] = dict(counters[name])
            elif typ == "gauge":
                entry["data"] = dict(gauges[name])
            else:
                bounds = tuple(meta.get("boundaries", ()))
                data: Dict[str, Any] = {}
                for labels, cell in hists[name].items():
                    if len(cell) != len(bounds) + 3:
                        continue
                    count = cell[len(bounds) + 2]
                    total = cell[len(bounds) + 1]
                    data[labels] = {
                        "count": count, "sum": total,
                        "mean": (total / count) if count else 0.0,
                        "buckets": {str(b): cell[i]
                                    for i, b in enumerate(bounds)},
                    }
                entry["data"] = data
                entry["boundaries"] = list(bounds)
                # Max-valued exemplar per label set: the dashboard's
                # link from a latency histogram to the slowest
                # request's retrievable trace.
                entry["exemplars"] = {
                    k: dict(v) for k, v in exemplars.get(name, {}).items()}
            out[name] = entry
        return out

    @staticmethod
    def _esc_label(v: str) -> str:
        return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
                .replace('"', '\\"'))

    def _expire_user_metric_sources(self) -> None:
        """Drop sources that stopped pushing (dead workers) after 10 flush
        intervals. Their counters/histograms — cumulative by contract — are
        folded into the tombstone accumulator first, so `rtpu_*_total`
        series keep their contribution and never go backwards on worker
        exit. Gauges are per-process state and are simply dropped."""
        ttl = GlobalConfig.metrics_report_interval_s * 10
        now = time.time()
        for source in [s for s, (ts, _) in self.user_metrics.items()
                       if now - ts > ttl]:
            _, records = self.user_metrics.pop(source)
            self._fold_tombstones(records)

    def _fold_tombstones(self, records) -> None:
        for rec in records:
            typ = rec.get("type")
            if typ not in ("counter", "histogram"):
                continue
            name = rec.get("name")
            tomb = self._metric_tombstones.get(name)
            if tomb is None:
                tomb = dict(rec)
                tomb["data"] = {
                    k: (list(v) if isinstance(v, list) else float(v))
                    for k, v in rec.get("data", {}).items()}
                self._metric_tombstones[name] = tomb
                continue
            if tomb.get("type") != typ or (
                    typ == "histogram"
                    and tuple(tomb.get("boundaries", ()))
                    != tuple(rec.get("boundaries", ()))):
                continue  # conflicting registration; skip, never crash
            data = tomb["data"]
            for tagvals, cell in rec.get("data", {}).items():
                prior = data.get(tagvals)
                if prior is None:
                    data[tagvals] = (list(cell) if isinstance(cell, list)
                                     else float(cell))
                elif isinstance(cell, list):
                    if len(prior) == len(cell):
                        for i, v in enumerate(cell):
                            prior[i] += v
                else:
                    data[tagvals] = float(prior) + float(cell)
            # Exemplars are max-keep, not additive: a dead worker's
            # slowest-request link stays until a live one beats it.
            ex = rec.get("exemplars") or {}
            if ex:
                tex = tomb.setdefault("exemplars", {})
                for tagvals, e in ex.items():
                    prior_ex = tex.get(tagvals)
                    if (prior_ex is None or float(e.get("value", 0.0))
                            >= float(prior_ex.get("value", 0.0))):
                        tex[tagvals] = dict(e)

    def _aggregate_user_metrics(self):
        """Merge pushed ray_tpu.util.metrics snapshots (live sources plus
        tombstones of expired ones): counters/histograms summed across
        processes, gauges kept per-process keyed by a pid label."""
        self._expire_user_metric_sources()
        # (name) -> merged view
        metas: Dict[str, Dict[str, Any]] = {}
        counters: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        gauges: Dict[str, Dict[str, float]] = defaultdict(dict)
        hists: Dict[str, Dict[str, List[float]]] = defaultdict(dict)
        # name -> labels -> max-valued exemplar across sources.
        exemplars: Dict[str, Dict[str, Dict[str, Any]]] = defaultdict(dict)
        # name -> newest push ts among live sources carrying it.
        fresh: Dict[str, float] = {}
        sources = list(self.user_metrics.items())
        if self._metric_tombstones:
            sources.append(
                ("(exited)", (0.0, list(self._metric_tombstones.values()))))
        for source, (push_ts, records) in sources:
            for rec in records:
                name, typ = rec["name"], rec["type"]
                if push_ts:  # tombstone pseudo-source pushes at ts 0.0
                    fresh[name] = max(fresh.get(name, 0.0), push_ts)
                meta = metas.setdefault(name, rec)
                if meta.get("type") != typ or (
                        typ == "histogram"
                        and tuple(meta.get("boundaries", ()))
                        != tuple(rec.get("boundaries", ()))):
                    # Conflicting registration from another process: skip
                    # this record rather than corrupt/crash the scrape.
                    continue
                keys = rec.get("tag_keys", ())
                for tagvals, cell in rec.get("data", {}).items():
                    labels = ",".join(
                        f'{k}="{self._esc_label(v)}"' for k, v in
                        zip(keys, tagvals.split(",") if keys else ()))
                    if typ == "counter":
                        counters[name][labels] += cell
                    elif typ == "gauge":
                        lbl = (labels + "," if labels else "") + \
                            f'pid="{self._esc_label(source)}"'
                        gauges[name][lbl] = cell
                    elif typ == "histogram":
                        acc = hists[name].get(labels)
                        if acc is None or len(acc) != len(cell):
                            hists[name][labels] = list(cell)
                        else:
                            for i, v in enumerate(cell):
                                acc[i] += v
                for tagvals, e in (rec.get("exemplars") or {}).items():
                    labels = ",".join(
                        f'{k}="{self._esc_label(v)}"' for k, v in
                        zip(keys, tagvals.split(",") if keys else ()))
                    prior = exemplars[name].get(labels)
                    if (prior is None or float(e.get("value", 0.0))
                            >= float(prior.get("value", 0.0))):
                        exemplars[name][labels] = dict(e)
        return metas, counters, gauges, hists, fresh, exemplars

    def _render_user_metrics(self) -> List[str]:
        """User metrics as Prometheus exposition lines."""
        metas, counters, gauges, hists, _, _ = \
            self._aggregate_user_metrics()
        out: List[str] = []
        for name, meta in metas.items():
            typ = meta["type"]
            full = f"rtpu_{name}"
            if meta.get("description"):
                out.append(f"# HELP {full} {meta['description']}")
            if typ in ("counter", "gauge"):
                out.append(f"# TYPE {full} {typ}")
                table = counters[name] if typ == "counter" else gauges[name]
                for labels, val in sorted(table.items()):
                    out.append(f"{full}{{{labels}}} {val}"
                               if labels else f"{full} {val}")
            elif typ == "histogram":
                out.append(f"# TYPE {full} histogram")
                bounds = meta.get("boundaries", ())
                for labels, cell in sorted(hists[name].items()):
                    if len(cell) != len(bounds) + 3:
                        continue  # mismatched push; never crash the scrape
                    prefix = labels + "," if labels else ""
                    for i, b in enumerate(bounds):
                        out.append(
                            f'{full}_bucket{{{prefix}le="{b}"}} {cell[i]}')
                    out.append(
                        f'{full}_bucket{{{prefix}le="+Inf"}} '
                        f'{cell[len(bounds)]}')
                    out.append(f"{full}_sum{{{labels}}} "
                               f"{cell[len(bounds) + 1]}"
                               if labels else
                               f"{full}_sum {cell[len(bounds) + 1]}")
                    out.append(f"{full}_count{{{labels}}} "
                               f"{cell[len(bounds) + 2]}"
                               if labels else
                               f"{full}_count {cell[len(bounds) + 2]}")
        return out

    def start_metrics_http(self, port: int = 0) -> int:
        """Serve GET /metrics for Prometheus scrapers (stdlib HTTP)."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        gcs = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                fut = get_io_loop().submit(gcs._h_metrics_text())
                body = fut.result(timeout=30).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer((self.server._host, port), _Handler)
        threading.Thread(target=server.serve_forever, daemon=True,
                         name="gcs-metrics-http").start()
        self._metrics_http = server
        port = server.server_address[1]
        self.kv["__internal__"]["metrics_port"] = str(port).encode()
        return port

    # ------------------------------------------------------------- node mgmt
    async def _h_register_node(self, node_id, addr, resources, labels,
                               object_store_capacity=0):
        self.nodes[node_id] = {
            "node_id": node_id,
            "addr": addr,  # (host, port) of the raylet RPC server
            "state": ALIVE,
            "labels": labels,
            "resources_total": resources,
            "object_store_capacity": object_store_capacity,
            "start_time": time.time(),
        }
        nr = NodeResources(ResourceSet(resources), labels)
        self.view.update_node(node_id, nr)
        self._view_seq += 1
        self._last_heartbeat[node_id] = time.monotonic()
        self.pubsub.publish("node", {"event": "ALIVE", "node_id": node_id,
                                     "addr": addr})
        self._record_event(
            "NODE_ADDED",
            f"node {node_id.hex()[:12]} joined at "
            f"{addr[0]}:{addr[1]} with {resources}",
            node_id=node_id.hex())
        return {"system_config": GlobalConfig.dump_system_config(),
                "nodes": self._nodes_snapshot()}

    async def _h_heartbeat(self, node_id, available, total, idle=True,
                           pending_demands=None, num_workers=0,
                           have_seq=0):
        if node_id not in self.nodes:
            return {"unknown": True}
        if os.environ.get("RAY_TPU_DEBUG_SCHED"):
            print(f"[gcs-hb {time.monotonic():.3f}] handled",
                  file=sys.stderr, flush=True)
        self._last_heartbeat[node_id] = time.monotonic()
        old = self.view.get(node_id)
        nr = NodeResources(ResourceSet(total), self.nodes[node_id]["labels"])
        nr.available = ResourceSet(available)
        if (old is None or old.available.to_dict() != nr.available.to_dict()
                or old.total.to_dict() != nr.total.to_dict()):
            self._view_seq += 1
        self.view.update_node(node_id, nr)
        self.nodes[node_id]["pending_demands"] = pending_demands or []
        self.nodes[node_id]["num_workers"] = num_workers
        if have_seq == self._view_seq:
            return {"seq": self._view_seq}
        return {"seq": self._view_seq, "nodes": self._nodes_snapshot()}

    async def _h_get_cluster_load(self):
        """Autoscaler state (reference: gcs_autoscaler_state_manager.h):
        per-node availability plus demands queued with no feasible home."""
        out = []
        for node_id, info in self.nodes.items():
            if info["state"] != ALIVE:
                continue
            nr = self.view.get(node_id)
            out.append({
                "node_id": node_id,
                "total": nr.total.to_dict() if nr else {},
                "available": nr.available.to_dict() if nr else {},
                "pending_demands": info.get("pending_demands", []),
                "num_workers": info.get("num_workers", 0),
                "labels": info.get("labels", {}),
            })
        return out

    def _nodes_snapshot(self):
        out = []
        for node_id, info in self.nodes.items():
            nr = self.view.get(node_id)
            out.append({
                "node_id": node_id,
                "addr": info["addr"],
                "state": info["state"],
                "labels": info["labels"],
                "total": nr.total.to_dict() if nr else {},
                "available": nr.available.to_dict() if nr else {},
            })
        return out

    async def _h_get_all_nodes(self):
        return self._nodes_snapshot()

    async def _h_drain_node(self, node_id):
        await self._mark_node_dead(node_id, "drained")
        return True

    async def _mark_node_dead(self, node_id, reason):
        info = self.nodes.get(node_id)
        if info is None or info["state"] == DEAD:
            return
        last = self._last_heartbeat.get(node_id)
        age = f"{time.monotonic() - last:.2f}s" if last else "never"
        print(f"[gcs] node {node_id.hex()[:8]} marked DEAD: {reason} "
              f"(last heartbeat {age} ago)", file=sys.stderr, flush=True)
        info["state"] = DEAD
        self.view.remove_node(node_id)
        self._view_seq += 1
        self.pubsub.publish("node", {"event": "DEAD", "node_id": node_id,
                                     "reason": reason})
        # A drain is operator intent; anything else is a failure.
        self._record_event(
            "NODE_REMOVED",
            f"node {node_id.hex()[:12]} marked DEAD: {reason} "
            f"(last heartbeat {age} ago)",
            severity="WARNING" if reason == "drained" else "ERROR",
            node_id=node_id.hex(), reason=reason)
        # Fail/restart actors that lived on this node.
        for actor_id, a in list(self.actors.items()):
            if a.get("node_id") == node_id and a["state"] == ALIVE:
                await self._on_actor_failure(actor_id, f"node died: {reason}")

    @staticmethod
    def _tcp_alive(addr, timeout=2.0) -> bool:
        import socket as _socket

        try:
            _socket.create_connection(tuple(addr), timeout=timeout).close()
            return True
        except ConnectionRefusedError:
            return False  # nothing listening: the process is gone
        except OSError:
            # Timeout / transient network error: INDETERMINATE — a
            # stalled raylet with a full accept backlog drops SYNs, and
            # calling that dead would re-create the mass-kill this probe
            # exists to prevent. Defer; the hard cap still bounds a
            # truly wedged node.
            return True

    async def _health_loop(self):
        """Passive heartbeat age + ACTIVE liveness probe (reference:
        gcs_health_check_manager.cc does an active per-node check, not
        just heartbeat bookkeeping). A stale heartbeat alone conflates
        BUSY with DEAD: on an oversubscribed host a raylet booting
        hundreds of workers can stall its loop past the passive
        threshold while its process is perfectly alive — observed as
        'node DEAD after 6.2s' mass-killing 86 healthy actors. The TCP
        probe discriminates: the kernel completes the handshake from
        the listen backlog even when the event loop is stalled, so
        connect-success means alive-but-busy (defer death, up to a
        hard cap) and connect-refused means the process is gone (die
        at the fast passive threshold, keeping node-failure detection
        prompt for real crashes)."""
        period = GlobalConfig.health_check_period_ms / 1000
        threshold = GlobalConfig.health_check_failure_threshold
        hard_cap = period * threshold * 12  # truly wedged: still dies
        deferred = set()
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            suspects = []
            for node_id, last in list(self._last_heartbeat.items()):
                info = self.nodes.get(node_id)
                if info is None or info["state"] == DEAD:
                    continue
                age = now - last
                if age <= period * threshold:
                    deferred.discard(node_id)
                    continue
                suspects.append((node_id, age, info["addr"]))
            if not suspects:
                continue
            # Probe CONCURRENTLY: N simultaneously-stale nodes (the
            # motivating storm) must not serialize into 2s x N sweeps
            # that delay detecting a genuinely crashed node.
            alive = await asyncio.gather(*[
                loop.run_in_executor(None, self._tcp_alive, addr)
                for _, _, addr in suspects])
            for (node_id, age, _addr), is_alive in zip(suspects, alive):
                # Re-check JUST before the kill decision: a heartbeat
                # can arrive during the probe window, and killing on
                # the stale snapshot shot a node whose last heartbeat
                # was 0.66s old (observed).
                last = self._last_heartbeat.get(node_id)
                if (last is not None
                        and time.monotonic() - last <= period * threshold):
                    deferred.discard(node_id)
                    continue
                if age < hard_cap and is_alive:
                    if node_id not in deferred:
                        deferred.add(node_id)
                        print(f"[gcs] node {node_id.hex()[:8]} heartbeat "
                              f"stale ({age:.1f}s) but TCP-alive; "
                              f"deferring death (busy host)",
                              file=sys.stderr, flush=True)
                    continue
                deferred.discard(node_id)
                await self._mark_node_dead(node_id, "health check failed")

    def _client_for_node(self, node_id) -> Optional[RpcClient]:
        info = self.nodes.get(node_id)
        if info is None or info["state"] == DEAD:
            return None
        if node_id not in self._node_clients:
            host, port = info["addr"]
            self._node_clients[node_id] = RpcClient(host, port)
        return self._node_clients[node_id]

    # --------------------------------------------------------------------- kv
    def _mark_dirty(self) -> None:
        self._snapshot_dirty = True

    async def _h_kv_put(self, namespace, key, value, overwrite=True):
        self._mark_dirty()
        ns = self.kv[namespace]
        if not overwrite and key in ns:
            return False
        ns[key] = value
        return True

    async def _h_kv_get(self, namespace, key):
        return self.kv[namespace].get(key)

    async def _h_kv_del(self, namespace, key):
        self._mark_dirty()
        return self.kv[namespace].pop(key, None) is not None

    async def _h_kv_keys(self, namespace, prefix=""):
        return [k for k in self.kv[namespace] if k.startswith(prefix)]

    async def _h_kv_exists(self, namespace, key):
        return key in self.kv[namespace]

    # ------------------------------------------------------------------ actors
    async def _h_register_actor(self, spec):
        """spec: pickled TaskSpec for the actor-creation task."""
        actor_id = spec.actor_id.binary()
        if actor_id in self.actors:
            # Duplicate delivery (client retried after a lost reply):
            # the first registration stands.
            return {"ok": True}
        name_key = (spec.actor_name, spec.namespace)
        if spec.actor_name:
            existing = self.named_actors.get(name_key)
            if existing is not None and self.actors[existing]["state"] != DEAD:
                return {"error": f"actor name {spec.actor_name!r} already taken",
                        "existing_actor_id": existing}
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "spec": spec,
            "state": PENDING_CREATION,
            "node_id": None,
            "addr": None,
            "worker_id": None,
            "restarts_used": 0,
            "name": spec.actor_name,
            "namespace": spec.namespace,
            "death_cause": None,
            "class_name": spec.function.qualname,
        }
        if spec.actor_name:
            self.named_actors[name_key] = actor_id
        self._actor_events[actor_id] = asyncio.Event()
        self._snapshot_dirty = True
        spawn_task(self._schedule_actor(actor_id))
        return {"ok": True}

    async def _h_register_actors(self, specs):
        """Batched registration: one round-trip for a whole fleet/gang
        bring-up.  A 500-actor storm previously paid 500 serialized RPC
        round-trips before the first worker lease went out; here every
        spec is admitted (and its scheduling task spawned) in one call.
        Replies are positional — one dict per spec, same contract as
        ``register_actor``."""
        return [await self._h_register_actor(spec) for spec in specs]

    async def _schedule_actor(self, actor_id):
        from ray_tpu._private.rpc import debug_log

        _dbg = debug_log(f"sched {actor_id.hex()[:6]}")
        a = self.actors[actor_id]
        spec = a["spec"]
        delay = 0.05
        deadline = time.monotonic() + GlobalConfig.worker_lease_timeout_ms / 1000
        while True:
            if a["state"] == DEAD:
                # kill() (or a node-death handler) resolved this actor
                # while it was pending — stop scheduling; never lease a
                # worker for a dead actor.
                return
            if time.monotonic() >= deadline:
                # Reference semantics: a FEASIBLE actor queues until
                # resources/worker slots free up (a 500-actor burst takes
                # minutes of worker spawns on a small host — that is
                # backlog, not failure). Only die when no node could ever
                # fit the demand.
                from ray_tpu._private.scheduling_policy import (
                    is_feasible_anywhere,
                )

                if spec.scheduling.kind == "PLACEMENT_GROUP":
                    pg = self.placement_groups.get(
                        spec.scheduling.placement_group_id)
                    if pg is None or pg.get("state") == "REMOVED":
                        break  # the PG is gone: this can never schedule
                if is_feasible_anywhere(self.view, spec.resources):
                    deadline = (time.monotonic()
                                + GlobalConfig.worker_lease_timeout_ms
                                / 1000)
                else:
                    break
            pg_res = None
            if spec.scheduling.kind == "PLACEMENT_GROUP":
                pg_res = self._pg_demand(spec.scheduling, spec.resources)
                if pg_res is None:
                    await asyncio.sleep(delay)
                    continue
            node_id = pick_node(self.view, spec.resources, spec.scheduling,
                                None, pg_res)
            if node_id is None:
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 1.0)
                continue
            client = self._client_for_node(node_id)
            _dbg("picked node", node_id.hex()[:6] if hasattr(node_id, 'hex') else node_id, "client", client is not None)
            if client is None:
                # view said schedulable but the node is gone/DEAD: the two
                # structures can lag during node death. MUST yield — a bare
                # continue here busy-spins the whole GCS event loop.
                self.view.remove_node(node_id)
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 1.0)
                continue
            try:
                reply = await client.acall(
                    "lease_worker_for_actor", spec=spec,
                    demand=(pg_res or spec.resources).to_dict(),
                    timeout=60)
                _dbg("lease reply", reply)
            except Exception as exc:
                _dbg("lease EXC", repr(exc))
                await asyncio.sleep(delay)
                continue
            if not reply.get("ok"):
                if reply.get("env_setup_error"):
                    # Creation can never succeed on this env; retrying just
                    # re-runs a failing pip install every cycle.
                    a["state"] = DEAD
                    a["death_cause"] = (
                        f"runtime_env setup failed: "
                        f"{reply['env_setup_error']}")
                    self._actor_events[actor_id].set()
                    self.pubsub.publish("actor", {
                        "actor_id": actor_id, "state": DEAD,
                        "cause": a["death_cause"]})
                    return
                await asyncio.sleep(delay)
                continue
            # Worker is up and dedicated; tell it to become the actor.
            worker_addr = reply["worker_addr"]
            worker_id = reply["worker_id"]
            wclient = RpcClient(*worker_addr)
            try:
                _dbg("create_actor ->", worker_addr)
                result = await wclient.acall("create_actor", spec=spec,
                                             tpu_ids=reply.get("tpu_ids", []),
                                             timeout=120)
                _dbg("create_actor reply", result)
            except Exception as exc:
                _dbg("create EXC", repr(exc))
                wclient.close()
                await asyncio.sleep(delay)
                continue
            if not result.get("ok"):
                a["state"] = DEAD
                a["death_cause"] = result.get("error", "actor __init__ failed")
                self._actor_events[actor_id].set()
                self.pubsub.publish("actor", {"actor_id": actor_id,
                                              "state": DEAD,
                                              "cause": a["death_cause"]})
                wclient.close()
                return
            if a["state"] == DEAD:
                # kill() raced with creation: tear the new worker down.
                try:
                    await wclient.acall("kill_self", timeout=5)
                except Exception:
                    pass
                wclient.close()
                return
            a.update(state=ALIVE, node_id=node_id, addr=tuple(worker_addr),
                     worker_id=worker_id)
            self._actor_events[actor_id].set()
            self._actor_events[actor_id] = asyncio.Event()
            self.pubsub.publish("actor", {"actor_id": actor_id, "state": ALIVE,
                                          "addr": worker_addr})
            wclient.close()
            return
        a["state"] = DEAD
        a["death_cause"] = "failed to schedule actor (no feasible node)"
        self._actor_events[actor_id].set()

    def _pg_demand(self, sched: SchedulingStrategySpec,
                   demand: ResourceSet) -> Optional[ResourceSet]:
        """Rewrite demand onto bundle-formatted resources (reference trick:
        tasks in a PG consume `name_group_{index}_{pg_id}` resources)."""
        pg = self.placement_groups.get(sched.placement_group_id)
        if pg is None or pg["state"] != "CREATED":
            return None
        from ray_tpu._private.resources import pg_task_demand

        return pg_task_demand(demand, sched.placement_group_id.hex(),
                              sched.bundle_index)

    async def _on_actor_failure(self, actor_id, cause):
        a = self.actors.get(actor_id)
        if a is None or a["state"] == DEAD:
            return
        spec = a["spec"]
        print(f"[gcs] actor {actor_id.hex()[:12]} failed "
              f"(restarts_used={a['restarts_used']}/{spec.max_restarts}): "
              f"{cause}", file=sys.stderr, flush=True)
        node_hex = (a.get("node_id") or b"").hex() or None
        if a["restarts_used"] < spec.max_restarts or spec.max_restarts == -1:
            a["restarts_used"] += 1
            a["state"] = RESTARTING
            a["addr"] = None
            self.pubsub.publish("actor", {"actor_id": actor_id,
                                          "state": RESTARTING})
            self._record_event(
                "ACTOR_RESTART",
                f"actor {actor_id.hex()[:12]} "
                f"({a.get('class_name', '')}) restarting "
                f"(restart {a['restarts_used']}/{spec.max_restarts}): "
                f"{cause}",
                node_id=node_hex, actor_id=actor_id.hex(), cause=str(cause))
            spawn_task(self._schedule_actor(actor_id))
        else:
            a["state"] = DEAD
            a["death_cause"] = cause
            self.pubsub.publish("actor", {"actor_id": actor_id, "state": DEAD,
                                          "cause": cause})
            intended = "killed via kill_actor" in str(cause)
            self._record_event(
                "ACTOR_DEATH",
                f"actor {actor_id.hex()[:12]} "
                f"({a.get('class_name', '')}) died: {cause}",
                # ray_tpu.kill is user intent, not a failure to page on.
                severity="INFO" if intended else "ERROR",
                node_id=node_hex, actor_id=actor_id.hex(), cause=str(cause))
            self._actor_events.setdefault(actor_id, asyncio.Event()).set()
            name_key = (a["name"], a["namespace"])
            if a["name"] and self.named_actors.get(name_key) == actor_id:
                del self.named_actors[name_key]

    async def _h_report_actor_death(self, actor_id, cause, from_node=None):
        await self._on_actor_failure(actor_id, cause)
        return True

    async def _h_wait_actor_ready(self, actor_id, wait_timeout=60.0):
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            a = self.actors.get(actor_id)
            if a is None:
                return {"error": "unknown actor"}
            if a["state"] == ALIVE:
                return {"state": ALIVE, "addr": a["addr"]}
            if a["state"] == DEAD:
                return {"state": DEAD, "cause": a["death_cause"]}
            ev = self._actor_events.get(actor_id)
            try:
                await asyncio.wait_for(asyncio.shield(ev.wait()),
                                       max(deadline - time.monotonic(), 0.01))
            except asyncio.TimeoutError:
                pass
        return {"error": "timeout"}

    async def _h_get_actor_info(self, actor_id):
        a = self.actors.get(actor_id)
        if a is None:
            return None
        return {k: a[k] for k in
                ("actor_id", "state", "node_id", "addr", "worker_id", "name",
                 "namespace", "death_cause", "restarts_used", "class_name")}

    async def _h_get_named_actor(self, name, namespace):
        actor_id = self.named_actors.get((name, namespace))
        if actor_id is None:
            return None
        # A name lookup hands a handle to a process the creator's local GC
        # cannot see — pin against creator-side garbage collection.
        self.actors[actor_id]["pinned_by_lookup"] = True
        info = await self._h_get_actor_info(actor_id)
        if info is not None:
            info["spec"] = self.actors[actor_id]["spec"]
        return info

    async def _h_gc_actor(self, actor_id):
        """Creator-side handle GC; unlike kill_actor this is advisory — a
        lookup-pinned or detached actor survives it."""
        a = self.actors.get(actor_id)
        if a is None:
            return False
        if a.get("pinned_by_lookup") or a["spec"].is_detached:
            return False
        return await self._h_kill_actor(actor_id, no_restart=True)

    async def _h_list_named_actors(self, namespace=None):
        return [
            {"name": n, "namespace": ns, "actor_id": aid}
            for (n, ns), aid in self.named_actors.items()
            if namespace is None or ns == namespace
        ]

    async def _h_list_actors(self):
        return [await self._h_get_actor_info(aid) for aid in self.actors]

    async def _h_kill_actor(self, actor_id, no_restart=True):
        a = self.actors.get(actor_id)
        if a is None:
            return False
        if no_restart:
            a["spec"].max_restarts = 0
        if a["addr"] is not None:
            client = RpcClient(*a["addr"])
            try:
                await client.acall("kill_self", timeout=5)
            except Exception:
                pass
            client.close()
        await self._on_actor_failure(actor_id, "killed via kill_actor")
        return True

    # ------------------------------------------------------- placement groups
    async def _h_create_placement_group(self, pg_id, bundles, strategy, name=""):
        """2-phase commit against raylets (reference:
        `gcs_placement_group_scheduler.h`, raylet PrepareBundles/CommitBundles
        at `placement_group_resource_manager.h:54-61`)."""
        if pg_id in self.placement_groups:
            return True    # duplicate delivery: first creation stands
        self.placement_groups[pg_id] = {
            "pg_id": pg_id, "bundles": bundles, "strategy": strategy,
            "name": name, "state": "PENDING", "bundle_nodes": [None] * len(bundles),
        }
        spawn_task(self._schedule_pg(pg_id))
        return True

    async def _schedule_pg(self, pg_id):
        pg = self.placement_groups[pg_id]
        bundles = [ResourceSet(b) for b in pg["bundles"]]
        strategy = pg["strategy"]
        deadline = time.monotonic() + GlobalConfig.worker_lease_timeout_ms / 1000
        while time.monotonic() < deadline and pg["state"] == "PENDING":
            placement = self._plan_pg(bundles, strategy)
            if placement is None:
                await asyncio.sleep(0.1)
                continue
            # Phase 1: prepare all bundles.
            prepared = []
            ok = True
            for idx, node_id in enumerate(placement):
                client = self._client_for_node(node_id)
                if client is None:
                    ok = False
                    break
                try:
                    r = await client.acall(
                        "prepare_bundle", pg_id=pg_id, bundle_index=idx,
                        resources=bundles[idx].to_dict(), timeout=30)
                    if not r:
                        ok = False
                        break
                    prepared.append((idx, node_id))
                except Exception:
                    ok = False
                    break
            if not ok:
                for idx, node_id in prepared:
                    client = self._client_for_node(node_id)
                    if client:
                        try:
                            await client.acall("return_bundle", pg_id=pg_id,
                                               bundle_index=idx, timeout=10)
                        except Exception:
                            pass
                await asyncio.sleep(0.1)
                continue
            # Phase 2: commit.
            for idx, node_id in enumerate(placement):
                client = self._client_for_node(node_id)
                await client.acall("commit_bundle", pg_id=pg_id,
                                   bundle_index=idx, timeout=30)
            pg["bundle_nodes"] = list(placement)
            pg["state"] = "CREATED"
            self.pubsub.publish("pg", {"pg_id": pg_id, "state": "CREATED"})
            return
        if pg["state"] == "PENDING":
            pg["state"] = "INFEASIBLE"
            self.pubsub.publish("pg", {"pg_id": pg_id, "state": "INFEASIBLE"})

    def _plan_pg(self, bundles: List[ResourceSet], strategy: str
                 ) -> Optional[List[bytes]]:
        """Bin-pack bundles onto nodes honoring PACK/SPREAD/STRICT_*."""
        avail = {nid: ResourceSet(nr.available.to_dict())
                 for nid, nr in self.view.nodes.items()}
        if not avail:
            return None
        placement: List[Optional[bytes]] = [None] * len(bundles)
        order = sorted(avail.keys())

        def fits(nid, demand):
            return avail[nid].is_superset_of(demand)

        if strategy == "STRICT_PACK":
            for nid in order:
                total = ResourceSet({})
                for b in bundles:
                    total = total.add(b)
                if fits(nid, total):
                    return [nid] * len(bundles)
            return None

        if strategy == "STRICT_SPREAD":
            if len(bundles) > len(order):
                return None
            used = set()
            for i, b in enumerate(bundles):
                chosen = None
                for nid in order:
                    if nid not in used and fits(nid, b):
                        chosen = nid
                        break
                if chosen is None:
                    return None
                used.add(chosen)
                placement[i] = chosen
            return placement

        # PACK (best effort pack) / SPREAD (best effort spread)
        prefer_spread = strategy == "SPREAD"
        last = None
        for i, b in enumerate(bundles):
            candidates = [n for n in order if fits(n, b)]
            if not candidates:
                return None
            if prefer_spread:
                fresh = [n for n in candidates if n != last]
                chosen = (fresh or candidates)[0]
            else:
                chosen = candidates[0] if last is None or last not in candidates \
                    else last
            placement[i] = chosen
            avail[chosen] = avail[chosen].subtract(b)
            last = chosen
        return placement

    async def _h_remove_placement_group(self, pg_id):
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return False
        for idx, node_id in enumerate(pg["bundle_nodes"]):
            if node_id is None:
                continue
            client = self._client_for_node(node_id)
            if client is not None:
                try:
                    await client.acall("return_bundle", pg_id=pg_id,
                                       bundle_index=idx, timeout=10)
                except Exception:
                    pass
        pg["state"] = "REMOVED"
        return True

    async def _h_get_placement_group(self, pg_id):
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return None
        return {k: pg[k] for k in ("pg_id", "bundles", "strategy", "name",
                                   "state", "bundle_nodes")}

    async def _h_wait_placement_group_ready(self, pg_id, wait_timeout=60.0):
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                return {"error": "unknown placement group"}
            if pg["state"] in ("CREATED", "INFEASIBLE", "REMOVED"):
                return {"state": pg["state"]}
            await asyncio.sleep(0.02)
        return {"state": "PENDING"}

    async def _h_list_placement_groups(self):
        return [await self._h_get_placement_group(p)
                for p in self.placement_groups]

    # -------------------------------------------------------------------- jobs
    async def _h_next_job_id(self):
        self._next_job_int += 1
        return self._next_job_int

    async def _h_register_job(self, job_id, driver_addr, metadata=None):
        self._mark_dirty()
        self.jobs[job_id] = {"job_id": job_id, "driver_addr": driver_addr,
                             "metadata": metadata or {}, "state": "RUNNING",
                             "start_time": time.time()}
        self._record_event("JOB_STARTED",
                           f"job {job_id.hex()} registered by driver at "
                           f"{driver_addr[0]}:{driver_addr[1]}",
                           job_id=job_id.hex())
        return True

    async def _h_mark_job_finished(self, job_id):
        self._mark_dirty()
        if job_id in self.jobs:
            self.jobs[job_id]["state"] = "FINISHED"
            self.jobs[job_id]["end_time"] = time.time()
            self._record_event("JOB_FINISHED",
                               f"job {job_id.hex()} finished",
                               job_id=job_id.hex())
        return True

    async def _h_list_jobs(self):
        return list(self.jobs.values())

    async def _h_get_job_info(self, job_id):
        return self.jobs.get(job_id)

    # ------------------------------------------------------------------ pubsub
    async def _h_publish(self, channel, message):
        self.pubsub.publish(channel, message)
        return True

    async def _h_poll(self, channel, cursor, wait_timeout=10.0):
        return await self.pubsub.poll(channel, cursor, wait_timeout)

    async def _h_pubsub_seq(self):
        """Current global sequence — subscribe-from-now cursor for late
        joiners (a new driver must not replay old worker logs)."""
        return self.pubsub._seq

    # ------------------------------------------------------------- task events
    async def _h_push_task_events(self, events):
        self.task_events.extend(events)
        for e in events:
            self._task_event_counts[e.get("state", "UNKNOWN")] += 1
            # Trace-tagged spans additionally feed the tail-sampled
            # trace store (they stay in the ring for the timeline too).
            if e.get("state") == "SPAN" and e.get("trace_id"):
                self.trace_store.add_span(e)
        return True

    async def _h_get_task_events(self, job_id=None, limit=1000):
        out = [e for e in self.task_events
               if job_id is None or e.get("job_id") == job_id]
        return out[-limit:]

    # ------------------------------------------------------------------ traces
    async def _h_get_trace(self, trace_id):
        return self.trace_store.get(trace_id)

    async def _h_list_traces(self, limit=100):
        return self.trace_store.summaries(limit=limit)

    async def _h_trace_stats(self):
        return self.trace_store.stats()

    # ----------------------------------------------------------------- workers
    async def _h_register_worker(self, worker_id, info):
        self.workers[worker_id] = info
        return True

    async def _h_list_workers(self):
        return list(self.workers.values())

    # ------------------------------------------------------------------- misc
    async def _h_get_system_config(self):
        return GlobalConfig.dump_system_config()

    async def _h_cluster_resources(self):
        total = ResourceSet({})
        for nr in self.view.nodes.values():
            total = total.add(nr.total)
        return total.to_dict()

    async def _h_available_resources(self):
        total = ResourceSet({})
        for nr in self.view.nodes.values():
            total = total.add(nr.available)
        return total.to_dict()

    async def _h_internal_stats(self):
        return {"event_stats": self.server.stats.snapshot(),
                "num_nodes": len([n for n in self.nodes.values()
                                  if n["state"] == ALIVE]),
                "num_actors": len(self.actors)}


def main():
    # SIGUSR1 dumps all thread stacks to stderr (the daemon log) — the
    # first tool for a wedged control plane (reference: ray's SIGTERM
    # stack-dump handlers in util/logging).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    # SIGUSR2 dumps parked-coroutine stacks + submit-queue state for
    # every event loop — faulthandler can't see awaits (rpc.py).
    from ray_tpu._private.rpc import install_coroutine_dump_signal
    install_coroutine_dump_signal()

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--system-config", default="{}")
    parser.add_argument("--fate-share-pid", type=int, default=0)
    # Identification only (lets `pkill -f <session-dir>` target one cluster).
    parser.add_argument("--session-dir", default="")
    args = parser.parse_args()

    GlobalConfig.load_system_config(args.system_config)
    from ray_tpu._private.fate_share import watch_parent

    watch_parent(args.fate_share_pid)
    gcs = GcsServer(args.host, args.port)
    if args.session_dir:
        gcs.enable_snapshots(
            os.path.join(args.session_dir, "gcs_snapshot.pkl"))

        def _final_snapshot(*_):
            try:
                gcs._write_snapshot(gcs._build_snapshot())
            except Exception:
                pass
            os._exit(0)

        import signal

        signal.signal(signal.SIGTERM, _final_snapshot)
    port = gcs.start()
    metrics_port = gcs.start_metrics_http()
    # Parent discovers the ports from stdout.
    print(f"GCS_PORT={port}", flush=True)
    print(f"METRICS_PORT={metrics_port}", flush=True)
    import threading
    threading.Event().wait()


if __name__ == "__main__":
    main()
