"""Control-plane RPC: asyncio TCP with length-prefixed pickled frames.

Role-equivalent to the reference's `src/ray/rpc/` gRPC scaffolding plus the
instrumented asio event loop (`asio/instrumented_io_context.h:27`,
`event_stats.h:104`): every server lives on a dedicated event-loop thread, all
handler invocations are latency-tracked, and clients support concurrent
in-flight calls with per-call timeouts and automatic reconnect.

This plane is hardware-agnostic (DCN-level) by design — tensors NEVER travel
here; they move via XLA collectives inside jitted programs (see
ray_tpu.util.collective) or through the shared-memory object store.

Wire format: 8-byte big-endian length || pickle((req_id, kind, method, payload)).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import pickle
import socket
import struct
import threading
import time
import traceback
from collections import defaultdict, deque
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

_HEADER = struct.Struct(">Q")

_KIND_REQUEST = 0
_KIND_RESPONSE = 1
_KIND_ERROR = 2

# Payloads bigger than this are rejected to catch framing corruption early.
_MAX_FRAME = 1 << 33


# Strong references for fire-and-forget tasks. asyncio's loop holds only
# WEAK references to tasks (see the create_task docs warning): a pending
# task whose await-chain isn't externally reachable can be garbage-
# collected mid-execution and silently vanish — under suite-level GC
# pressure this kills daemons (lease dispatchers, read loops, GCS
# schedulers) and everything downstream wedges. Every fire-and-forget
# spawn in the runtime goes through spawn_task() so the task is pinned
# until done.
_BACKGROUND_TASKS: set = set()

# Every live EventLoopThread, for wedge diagnostics (dump_event_loops).
import weakref as _weakref  # noqa: E402

_ALL_LOOPS: "_weakref.WeakSet" = _weakref.WeakSet()


def dump_event_loops(file=None) -> None:
    """Wedge diagnostic: for every EventLoopThread in this process, print
    its submit-queue state and the *coroutine* stack of every parked
    asyncio task. faulthandler shows only OS-thread stacks — an idle
    `select()` loop with twenty tasks awaiting lost replies looks
    healthy in a thread dump; this shows where each coroutine actually
    awaits. Best-effort and lock-free: meant to run from a signal
    handler in a process that may be wedged."""
    import io as _io
    import sys

    out = _io.StringIO()
    for elt in list(_ALL_LOOPS):
        try:
            thread = getattr(elt, "_thread", None)
            out.write(
                f"--- EventLoopThread {getattr(thread, 'name', '?')} "
                f"alive={bool(thread and thread.is_alive())} "
                f"pending={len(elt._pending)} "
                f"drain_scheduled={elt._drain_scheduled} "
                f"inflight={len(elt._inflight)} "
                f"stopped={elt._stopped}\n")
            # all_tasks iterates a WeakSet the live loop mutates
            # concurrently — "Set changed size during iteration"
            # RuntimeErrors are transient, so retry a few times before
            # giving up on this loop's task list.
            tasks = None
            err = None
            for _ in range(5):
                try:
                    tasks = [t for t in asyncio.all_tasks(elt.loop)]
                    break
                except RuntimeError as e:
                    err = e
                    continue
                except Exception as e:
                    err = e
                    break
            if tasks is None:
                out.write(f"    (all_tasks failed: {err!r})\n")
                continue
            for t in tasks:
                try:
                    coro = t.get_coro()
                    name = getattr(coro, "__qualname__", repr(coro))
                    out.write(f"  task {name} done={t.done()}\n")
                    for frame in t.get_stack(limit=16):
                        code = frame.f_code
                        out.write(
                            f"    {code.co_filename}:{frame.f_lineno} "
                            f"in {code.co_name}\n")
                except Exception as e:
                    out.write(f"  (task dump failed: {e!r})\n")
        except Exception as e:
            out.write(f"--- (loop dump failed: {e!r})\n")
    (file or sys.stderr).write(out.getvalue())
    try:
        (file or sys.stderr).flush()
    except Exception:
        pass


def dump_thread_stacks(file=None) -> None:
    """Wedge diagnostic companion to dump_event_loops: the *OS-thread*
    Python stacks of every thread in this process. A wedged worker
    blocked in user code (a lock, a collective, a C extension holding
    the GIL between bytecodes) never shows up in the coroutine dump —
    this is the half that does. Lock-free and best-effort, implemented
    inline (no imports at dump time): a signal handler in a wedged
    process must not touch the import machinery."""
    import io as _io
    import sys
    import threading
    import traceback

    out = _io.StringIO()
    out.write(f"--- Python thread stacks (pid {os.getpid()}, "
              f"{threading.active_count()} threads) ---\n")
    try:
        frames = sys._current_frames()
    except Exception as e:  # noqa: BLE001
        frames = {}
        out.write(f"    (sys._current_frames failed: {e!r})\n")
    names = {t.ident: t for t in threading.enumerate()}
    for ident, frame in frames.items():
        t = names.get(ident)
        name = t.name if t else f"thread-{ident}"
        daemon = " daemon" if (t and t.daemon) else ""
        out.write(f"--- thread {name}{daemon} ---\n")
        try:
            out.write("".join(traceback.format_stack(frame)))
        except Exception as e:  # noqa: BLE001
            out.write(f"    (stack dump failed: {e!r})\n")
    (file or sys.stderr).write(out.getvalue())
    try:
        (file or sys.stderr).flush()
    except Exception:
        pass


def install_coroutine_dump_signal() -> None:
    """Register SIGUSR2 → dump_event_loops + dump_thread_stacks on
    stderr (the worker's .err file, so the raylet's worker_exit_tail
    capture includes a final stack on wedged-worker kills).
    Python-level handler (runs between bytecodes on the main thread):
    fine for the parked-coroutine wedge class where the loops are idle
    and the main thread sits in an interruptible wait."""
    import signal

    def _h(signum, frame):
        try:
            dump_event_loops()
        except Exception:
            pass
        try:
            dump_thread_stacks()
        except Exception:
            pass

    try:
        signal.signal(signal.SIGUSR2, _h)
    except (ValueError, OSError):
        pass  # non-main thread or unsupported platform


def spawn_task(coro: Awaitable, loop=None) -> "asyncio.Task":
    """ensure_future + a strong reference held until the task finishes."""
    task = asyncio.ensure_future(coro, loop=loop) if loop is not None \
        else asyncio.ensure_future(coro)
    _BACKGROUND_TASKS.add(task)
    task.add_done_callback(_BACKGROUND_TASKS.discard)
    return task


def debug_log(tag: str, env_var: str = "RAY_TPU_DEBUG_SCHED"):
    """Env-gated stderr debug logger shared by the runtime daemons."""
    import sys

    if not os.environ.get(env_var):
        return lambda *m: None
    return lambda *m: print(f"[{tag} {time.monotonic():.3f}]", *m,
                            file=sys.stderr, flush=True)


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


# Methods the sync transport may safely RE-SEND after a connection drop
# mid-call: the server might have executed the first attempt with only the
# reply lost, so everything here must be a read, a keyed upsert, or a call
# the server dedups by id. Anything else (e.g. return_worker, refcount
# releases, id allocators) surfaces ConnectionLost to the caller instead.
_RETRY_SAFE_PREFIXES = (
    "get_", "list_", "kv_", "wait_", "cluster_", "available_", "node_",
    "store_", "metrics_", "contains_", "object_", "runtime_env_",
)
_RETRY_SAFE_METHODS = frozenset({
    "heartbeat", "ping", "client_ping", "poll", "pubsub_seq",
    "register_node", "register_worker", "register_actor", "register_job",
    "create_placement_group", "remove_placement_group",
    "create_object", "seal_object", "pin_object", "unpin_object",
    "kill_actor", "client_kill_actor", "client_cancel",
    "client_disconnect", "client_export_function", "client_get_actor",
    "mark_job_finished", "push_task_events",
    "add_borrower", "release_borrower",  # server-side key dedup
})


def _retry_safe(method: str) -> bool:
    return (method in _RETRY_SAFE_METHODS
            or method.startswith(_RETRY_SAFE_PREFIXES))


class ConnectionLost(Exception):
    pass


class TaskCancelled(RuntimeError):
    """Set on a submit() future whose coroutine was cancelled.

    Deliberately Exception-derived: on stock CPython >= 3.8,
    concurrent.futures.CancelledError aliases asyncio's
    BaseException-derived CancelledError, which would sail through
    every `except Exception` on the submitting thread."""


class EventStats:
    """Per-handler count/total-time tracking (reference: event_stats.h:104)."""

    def __init__(self):
        self._stats: Dict[str, Tuple[int, float]] = defaultdict(lambda: (0, 0.0))
        self._lock = threading.Lock()

    def record(self, name: str, elapsed: float) -> None:
        with self._lock:
            count, total = self._stats[name]
            self._stats[name] = (count + 1, total + elapsed)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                k: {"count": c, "total_s": t, "mean_s": t / c if c else 0.0}
                for k, (c, t) in self._stats.items()
            }


async def _read_frame(reader: asyncio.StreamReader):
    """Returns ((req_id, kind, method, payload), is_msgpack).

    Frames from Python peers are pickled (protocol >= 2, body starts
    0x80).  Cross-language clients (the C++ frontend, `cpp/`) send the
    same 4-tuple msgpack-encoded instead — a fixarray first byte, which
    can never collide with pickle's PROTO opcode.  Reference analogue:
    the msgpack boundary of `python/ray/cross_language.py`."""
    header = await reader.readexactly(_HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > _MAX_FRAME:
        raise ConnectionLost(f"oversized frame: {length}")
    body = await reader.readexactly(length)
    if body[:1] == b"\x80":
        return pickle.loads(body), False
    import msgpack

    req_id, kind, method, payload = msgpack.unpackb(body, raw=False)
    return (req_id, kind, method, payload), True


def _encode_frame(msg) -> bytes:
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(len(body)) + body


def _encode_msgpack_frame(msg) -> bytes:
    import msgpack

    body = msgpack.packb(list(msg), use_bin_type=True)
    return _HEADER.pack(len(body)) + body


class EventLoopThread:
    """An asyncio loop running on a daemon thread; sync-callable.

    submit() coalesces cross-thread wakeups: run_coroutine_threadsafe
    pays one self-pipe write syscall + selector wakeup PER CALL, so a
    burst of N task submissions from the driver thread wakes the loop N
    times (the reference's Cython core worker amortizes this in its C++
    io_context; our analogue is batching at the loop boundary). Here
    submissions append to a deque and only the empty→non-empty
    transition schedules one drain callback that starts the whole batch
    FIFO — submission order is preserved exactly as with
    run_coroutine_threadsafe."""

    def __init__(self, name: str = "ray_tpu-io"):
        _ALL_LOOPS.add(self)
        self.loop = asyncio.new_event_loop()
        self._pending: deque = deque()
        self._pending_lock = threading.Lock()
        self._drain_scheduled = False
        self._coalesce = os.environ.get(
            "RAY_TPU_SUBMIT_COALESCE", "1") != "0"
        self._stopped = False
        # Caller-side stop latch: set at stop() entry (NOT on the loop
        # thread) so submits racing a shutdown fail fast even when the
        # loop thread is wedged and _shutdown never runs.
        self._stop_requested = False
        # Futures whose coroutine was started but not yet resolved.
        # Mutated only on the loop thread; swept by stop() after the
        # thread is joined (so no concurrent mutation is possible).
        self._inflight: Dict[Any, Any] = {}
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro: Awaitable, timeout: Optional[float] = None):
        if threading.current_thread() is self._thread:
            # Blocking on our own loop can never complete — it stalls the
            # loop for the full timeout (observed: GCS heartbeat outages
            # from close() in handlers). Fail loudly instead.
            coro.close()
            raise RuntimeError(
                "EventLoopThread.run() called from its own loop thread; "
                "use 'await' or asyncio.ensure_future instead")
        return self.submit(coro).result(timeout)

    def submit(self, coro: Awaitable):
        """Schedule `coro` on the loop; returns a concurrent Future.

        Unlike run_coroutine_threadsafe, the returned future is NOT
        cancellable once the drain has started the coroutine (cancel()
        before that point works and the coroutine never runs). No
        current caller cancels submit() futures; holders that need a
        cancellable handle should signal the coroutine directly."""
        if self._stop_requested:
            # stop() has begun (possibly with the loop thread wedged in a
            # task's blocking call, so the loop may never drain again):
            # enqueueing would hang the caller forever. Fail fast.
            coro.close()
            raise RuntimeError("event loop stopping")
        if not self._coalesce:
            return asyncio.run_coroutine_threadsafe(coro, self.loop)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._pending_lock:
            self._pending.append((coro, fut))
            wake = not self._drain_scheduled
            if wake:
                self._drain_scheduled = True
        if wake:
            try:
                self.loop.call_soon_threadsafe(self._drain)
            except RuntimeError:
                # Loop already closed (shutdown race): fail the batch
                # instead of leaving callers waiting forever.
                self._fail_pending("event loop closed")
                raise
        return fut

    # Max submissions started per drain callback. Bounds the length of a
    # single loop iteration under a submit storm: timers (heartbeats)
    # and readable sockets are re-checked between chunks, so a fast
    # submitter can't make the loop unresponsive (measured: uncapped
    # batches reached tens of thousands, stretching iterations to
    # ~300 ms and starving 5 ms timers).
    _DRAIN_CHUNK = 256

    def _drain(self):
        if self._stopped:
            # A drain landing between _shutdown and the deferred
            # loop.stop must NOT start tasks — they would never get a
            # step and their futures would hang. Fail them instead.
            self._fail_pending("event loop stopping")
            return
        # One bounded batch per callback: remaining/new entries are
        # handled by a re-scheduled drain on the NEXT loop iteration, so
        # sustained cross-thread submission can't starve other loop work
        # (heartbeats, in-flight reads) the way an unbounded re-check
        # loop would.
        batch = []
        with self._pending_lock:
            while self._pending and len(batch) < self._DRAIN_CHUNK:
                batch.append(self._pending.popleft())
        for coro, fut in batch:
            if not fut.set_running_or_notify_cancel():
                coro.close()  # caller cancelled before we started it
                continue
            try:
                task = self.loop.create_task(coro)
            except Exception as e:
                fut.set_exception(e)
                continue
            self._inflight[task] = fut
            task.add_done_callback(
                lambda t, f=fut: self._copy_result(t, f))
        with self._pending_lock:
            if self._pending:
                self.loop.call_soon(self._drain)
            else:
                self._drain_scheduled = False

    def _copy_result(self, task: "asyncio.Task", fut) -> None:
        self._inflight.pop(task, None)
        if task.cancelled():
            fut.set_exception(TaskCancelled("coroutine cancelled"))
            return
        exc = task.exception()
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(task.result())

    def stop(self):
        self._stop_requested = True

        def _shutdown():
            self._stopped = True
            self._fail_pending("event loop stopping")
            tasks = list(asyncio.all_tasks(self.loop))
            for task in tasks:
                task.cancel()

            # Stop only after the cancellations have fully landed:
            # delivering CancelledError takes one loop iteration, and
            # the done-callbacks that resolve submit() futures run one
            # iteration after THAT — stopping immediately would strand
            # both. The gather resumes after every per-task done
            # callback already added (callbacks fire in add order), so
            # by the time we stop, every future is resolved. Bounded:
            # a task that swallows cancellation can't wedge stop().
            async def _stop_when_done():
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*tasks, return_exceptions=True),
                        timeout=2.0)
                except Exception:
                    pass
                self.loop.stop()

            spawn_task(_stop_when_done(), loop=self.loop)

        try:
            self.loop.call_soon_threadsafe(_shutdown)
        except RuntimeError:
            pass  # loop already closed (double stop)
        self._thread.join(timeout=5)
        # Close the loop so later submit()s fail fast in
        # call_soon_threadsafe instead of silently enqueueing onto a
        # dead loop (only if the thread really exited — closing a
        # running loop raises).
        if not self._thread.is_alive():
            try:
                self.loop.close()
            except RuntimeError:
                pass
        # Submissions racing between _shutdown's flush and the close
        # above would be orphaned (their call_soon'd drain never runs)
        # — flush again now that the loop is down.
        self._fail_pending("event loop stopped")
        # Backstop for started-but-unresolved coroutines: a task whose
        # final done-callback didn't get a loop iteration (e.g. its
        # chunk completed in the same iteration the stop task first
        # ran) would leave its future RUNNING forever. The loop thread
        # is dead here, so sweeping is race-free.
        if not self._thread.is_alive():
            for task, fut in list(self._inflight.items()):
                if fut.done():
                    continue
                if task.done():
                    # The task finished; only its done-callback missed
                    # the loop — deliver the REAL outcome, not a bogus
                    # shutdown error that could trigger spurious
                    # retries of work that actually executed.
                    self._copy_result(task, fut)
                else:
                    fut.set_exception(RuntimeError("event loop stopped"))
            self._inflight.clear()

    def _fail_pending(self, reason: str) -> None:
        with self._pending_lock:
            batch = list(self._pending)
            self._pending.clear()
            self._drain_scheduled = False
        for coro, fut in batch:
            coro.close()
            if fut.set_running_or_notify_cancel():
                fut.set_exception(RuntimeError(reason))


_global_loop: Optional[EventLoopThread] = None
_global_loop_lock = threading.Lock()


def get_io_loop() -> EventLoopThread:
    global _global_loop
    with _global_loop_lock:
        if _global_loop is None or not _global_loop._thread.is_alive():
            _global_loop = EventLoopThread()
        return _global_loop


Handler = Callable[..., Awaitable[Any]]


class RpcServer:
    """Serves registered async handlers over TCP.

    Handlers have signature ``async def handler(**payload) -> reply``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 io: Optional[EventLoopThread] = None):
        self._host = host
        self._requested_port = port
        self._handlers: Dict[str, Handler] = {}
        self._io = io or get_io_loop()
        self._server: Optional[asyncio.AbstractServer] = None
        self.stats = EventStats()
        self.port: Optional[int] = None

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def register_service(self, service: object, prefix: str = "") -> None:
        """Register every public async method of an object."""
        for name in dir(service):
            if name.startswith("_"):
                continue
            fn = getattr(service, name)
            if asyncio.iscoroutinefunction(fn):
                self._handlers[prefix + name] = fn

    def start(self) -> int:
        async def _start():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._requested_port
            )
            return self._server.sockets[0].getsockname()[1]

        self.port = self._io.run(_start())
        return self.port

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self.port)

    async def _handle_conn(self, reader, writer):
        write_lock = asyncio.Lock()
        try:
            while True:
                try:
                    (req_id, kind, method,
                     payload), is_mp = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError,
                        ConnectionLost):
                    break
                except Exception as exc:
                    # Undecodable frame (bad cross-language client or a
                    # pickle the server can't load): framing is
                    # unrecoverable on this connection. Log before
                    # killing it — every in-flight call on the shared
                    # connection is about to see ConnectionLost.
                    import sys

                    print(f"[rpc] closing connection on undecodable "
                          f"frame: {exc!r}", file=sys.stderr, flush=True)
                    break
                if kind != _KIND_REQUEST:
                    continue
                spawn_task(
                    self._dispatch(req_id, method, payload, writer,
                                   write_lock, is_mp)
                )
        finally:
            writer.close()

    async def _dispatch(self, req_id, method, payload, writer, write_lock,
                        is_mp=False):
        start = time.monotonic()
        try:
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"no handler registered for {method!r}")
            reply = await handler(**payload)
            if is_mp:
                # Cross-language caller: reply must stay in msgpack types
                # (the xlang handlers guarantee this).
                frame = _encode_msgpack_frame(
                    (req_id, _KIND_RESPONSE, method, reply))
            else:
                frame = _encode_frame(
                    (req_id, _KIND_RESPONSE, method, reply))
        except Exception as exc:  # noqa: BLE001 — forwarded to caller
            if is_mp:
                frame = _encode_msgpack_frame(
                    (req_id, _KIND_ERROR, method,
                     [type(exc).__name__, str(exc),
                      traceback.format_exc()]))
            else:
                err = (type(exc).__name__, str(exc),
                       traceback.format_exc(), exc)
                try:
                    frame = _encode_frame((req_id, _KIND_ERROR, method, err))
                except Exception:
                    # Exception object itself unpicklable — string form only.
                    frame = _encode_frame((req_id, _KIND_ERROR, method,
                                           (type(exc).__name__, str(exc),
                                            traceback.format_exc(), None)))
        finally:
            self.stats.record(method, time.monotonic() - start)
        try:
            async with write_lock:
                writer.write(frame)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, RuntimeError):
            pass

    def stop(self):
        async def _stop():
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()

        try:
            self._io.run(_stop(), timeout=5)
        except Exception:
            pass


class _SyncConn:
    """A blocking request/response socket for one calling thread.

    One request in flight at a time (per-thread), so replies never
    interleave and no framing state is needed beyond the length prefix.
    """

    __slots__ = ("host", "port", "_connect_timeout", "sock", "dead",
                 "owner_thread")

    def __init__(self, host: str, port: int, connect_timeout: float):
        self.host, self.port = host, port
        self._connect_timeout = connect_timeout
        self.sock = None
        self.dead = False
        self.owner_thread = threading.get_ident()
        self._connect()

    def _connect(self):
        deadline = time.monotonic() + self._connect_timeout
        delay = 0.05
        while True:
            try:
                self.sock = socket.create_connection(
                    (self.host, self.port), timeout=self._connect_timeout)
                break
            except OSError:
                if time.monotonic() > deadline:
                    self.dead = True
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionLost(
                    f"connection to {self.host}:{self.port} closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks) if len(chunks) != 1 else chunks[0]

    def call(self, method: str, payload: dict, timeout: Optional[float]):
        frame = _encode_frame((0, _KIND_REQUEST, method, payload))
        try:
            try:
                kind, reply = self._roundtrip(frame, timeout)
            except (ConnectionLost, BrokenPipeError, ConnectionResetError,
                    OSError) as first:
                if isinstance(first, socket.timeout):
                    raise
                if not _retry_safe(method):
                    # The server may have executed the request and only the
                    # reply was lost; re-sending a non-idempotent method
                    # would double-execute it (e.g. a duplicated
                    # return_worker offers the same worker handle twice).
                    # Surface the loss instead and let the caller decide.
                    raise
                # Server bounced while this pooled connection sat idle (or
                # died before replying). Reconnect once and retry — only
                # for methods on the idempotent allowlist (reads, keyed
                # upserts with server-side dedup); a restarted control
                # plane is exactly the case this retry exists for.
                self.close()
                self.dead = False
                self._connect()
                kind, reply = self._roundtrip(frame, timeout)
        except socket.timeout:
            # The reply may still arrive later; this connection's framing
            # is now out of step — discard it.
            self.close()
            raise TimeoutError(
                f"rpc {method} to {self.host}:{self.port} timed out "
                f"after {timeout}s") from None
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            self.close()
            raise ConnectionLost(
                f"connection to {self.host}:{self.port} lost: {e}") from None
        except ConnectionLost:
            self.close()
            raise
        if kind == _KIND_RESPONSE:
            return reply
        name, msg, tb, exc = reply
        if exc is not None and isinstance(exc, Exception):
            raise exc
        raise RpcError(f"{name}: {msg}\n{tb}")

    def _roundtrip(self, frame: bytes, timeout: Optional[float]):
        self.sock.settimeout(self._connect_timeout)
        self.sock.sendall(frame)
        self.sock.settimeout(timeout)
        header = self._recv_exact(_HEADER.size)
        (length,) = _HEADER.unpack(header)
        if length > _MAX_FRAME:
            raise ConnectionLost(f"oversized frame: {length}")
        _req_id, kind, _method, reply = pickle.loads(
            self._recv_exact(length))
        return kind, reply

    def close(self):
        self.dead = True
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass


class RpcClient:
    """Persistent connection to one RpcServer; thread-safe concurrent calls."""

    def __init__(self, host: str, port: int,
                 io: Optional[EventLoopThread] = None,
                 connect_timeout: float = 10.0):
        self.host, self.port = host, port
        self._io = io or get_io_loop()
        self._connect_timeout = connect_timeout
        self._reader = None
        self._writer = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock: Optional[asyncio.Lock] = None
        self._write_lock: Optional[asyncio.Lock] = None
        self._closed = False
        self._sync_local = threading.local()
        self._sync_conns: list = []
        self._sync_conns_lock = threading.Lock()

    async def _ensure_connected(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
            self._write_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            # The loop that creates the connection owns it; close() must
            # route the transport close back here.
            self._owner_loop = asyncio.get_running_loop()
            deadline = time.monotonic() + self._connect_timeout
            delay = 0.05
            while True:
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self.host, self.port
                    )
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 1.0)
            spawn_task(self._read_loop(self._reader))

    async def _read_loop(self, reader):
        try:
            while True:
                (req_id, kind, method,
                 payload), _is_mp = await _read_frame(reader)
                fut = self._pending.pop(req_id, None)
                if fut is None or fut.done():
                    continue
                if kind == _KIND_RESPONSE:
                    fut.set_result(payload)
                else:
                    name, msg, tb, exc = payload
                    if exc is not None and isinstance(exc, Exception):
                        fut.set_exception(exc)
                    else:
                        fut.set_exception(RpcError(f"{name}: {msg}\n{tb}"))
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ConnectionLost, Exception):
            self._writer = None
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(
                        f"connection to {self.host}:{self.port} lost"))
            self._pending.clear()

    async def acall(self, method: str, timeout: Optional[float] = None, **payload):
        await self._ensure_connected()
        self._next_id += 1
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        frame = _encode_frame((req_id, _KIND_REQUEST, method, payload))
        async with self._write_lock:
            self._writer.write(frame)
            await self._writer.drain()
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def cast(self, method: str, timeout: Optional[float] = 10.0,
             **payload):
        """Fire-and-forget call: schedule `method` on the io loop and
        return immediately; the reply (and any error) is swallowed.

        For telemetry-grade RPCs on hot paths — train-step heartbeats,
        metric rows — where the caller must never block on, or fail
        because of, the control plane. The ``timeout`` still bounds the
        in-flight call so a dead peer cannot accumulate pending futures.
        """

        async def _fire():
            try:
                await self.acall(method, timeout=timeout, **payload)
            except Exception:
                pass  # best-effort by contract

        try:
            self._io.submit(_fire())
        except RuntimeError:
            pass  # io loop stopping: drop, same contract

    def call(self, method: str, timeout: Optional[float] = None, **payload):
        """Blocking call from any non-loop thread.

        Runs over a dedicated per-thread blocking socket rather than the
        shared asyncio connection: a sync caller otherwise pays two
        thread↔loop handoffs per call (~ms-class on a loaded host), which
        dominated the put/get hot path.
        """
        if threading.current_thread() is self._io._thread:
            raise RuntimeError(
                f"RpcClient.call({method!r}) from the io-loop thread would "
                "stall the loop; use 'await client.acall(...)' instead")
        conn = getattr(self._sync_local, "conn", None)
        if conn is None or conn.dead:
            conn = _SyncConn(self.host, self.port, self._connect_timeout)
            self._sync_local.conn = conn
            with self._sync_conns_lock:
                # Prune sockets owned by exited threads (their thread-local
                # ref is gone but this registry would otherwise pin the fd
                # open for the life of the client).
                live = {t.ident for t in threading.enumerate()}
                keep = []
                for c in self._sync_conns:
                    if c.dead:
                        continue
                    if c.owner_thread not in live:
                        c.close()
                        continue
                    keep.append(c)
                keep.append(conn)
                self._sync_conns = keep
        return conn.call(method, payload, timeout)

    def close(self):
        self._closed = True
        with self._sync_conns_lock:
            conns, self._sync_conns = self._sync_conns, []
        for conn in conns:
            conn.close()

        async def _close():
            if self._writer is not None:
                self._writer.close()

        try:
            owner = getattr(self, "_owner_loop", None)
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            if owner is None:
                return    # never connected; nothing to close
            if current is owner:
                # Closing from the owning loop (a GCS handler, a
                # dashboard handler): blocking would stall the loop for
                # the full timeout — heartbeats stop, nodes get declared
                # dead. Schedule and return.
                spawn_task(_close())
            else:
                # Transports are loop-affine: hand the close to the loop
                # that created the connection, without blocking if we are
                # ourselves on some other loop.
                fut = asyncio.run_coroutine_threadsafe(_close(), owner)
                if current is None:
                    fut.result(2)
        except Exception:
            pass
