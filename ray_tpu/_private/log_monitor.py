"""Per-node log aggregation: tail worker logs, publish lines to drivers.

Reference: `python/ray/_private/log_monitor.py:103` — a per-node monitor
tails `session_latest/logs/*` and publishes new lines over GCS pubsub;
every driver subscribes and echoes them, which is how a `print` inside a
remote task shows up on the driver's terminal.

Here the monitor runs as an async task inside the raylet (no extra
process): it scans `{session_dir}/logs/worker-*.out` and `worker-*.err`,
remembers a byte offset per file, and publishes batches of complete
lines on the "logs" pubsub channel (stderr batches carry ``is_err`` so
the driver renders them distinctly). Runtime noise (jax backend preload
warnings every worker emits at import) is filtered before publishing.

Per-task attribution: workers bracket each executing task with marker
lines (``task_marker``/``task_end_marker``) in their own log stream.
The monitor consumes the markers (never echoed) and tags every
published batch with the task/actor the lines belong to; the same
marker protocol lets ``read_task_lines`` reconstruct one task's output
from a full log file for ``util.state.get_log(task_id=...)``.
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Optional, Tuple

# Lines every spawned worker emits on interpreter start that carry no
# user signal; echoing them once per worker would drown the driver.
_NOISE = [
    re.compile(rb"WARNING:.*xla_bridge.*experimental"),
    re.compile(rb"^\s*$"),
]

_FILE_RE = re.compile(r"worker-([0-9a-f]+)\.(out|err)$")

# Per-file, per-scan read cap: a crash-looping task spewing hundreds of MB
# must not block the raylet event loop in one read() or ship a single
# giant pubsub message. The remainder is picked up next scan.
MAX_READ_PER_SCAN = 256 * 1024

# ---------------------------------------------------------------- markers
# Worker-side task attribution protocol: `::rtpu:task:<task_id_hex>:
# <actor_id_hex or ->:<name>::` opens a task's output span in the
# stream, `::rtpu:task:end:<task_id_hex>::` closes it. Markers are
# consumed here — they never reach the driver terminal.
_MARKER_PREFIX = "::rtpu:task:"
_MARKER_RE = re.compile(
    rb"^::rtpu:task:(end:)?([0-9a-f]+)(?::([0-9a-f-]*):(.*?))?::\s*$")


def task_marker(task_id_hex: str, actor_id_hex: str = "",
                name: str = "") -> str:
    # The name rides along for future use but must not break parsing.
    safe_name = name.replace(":", "_").replace("\n", " ")
    return (f"{_MARKER_PREFIX}{task_id_hex}:{actor_id_hex or '-'}:"
            f"{safe_name}::")


def task_end_marker(task_id_hex: str) -> str:
    return f"{_MARKER_PREFIX}end:{task_id_hex}::"


def _parse_marker(line: bytes) -> Optional[Tuple[bool, str, str]]:
    """Returns (is_end, task_id_hex, actor_id_hex) or None."""
    m = _MARKER_RE.match(line.strip())
    if not m:
        return None
    is_end = m.group(1) is not None
    actor = (m.group(3) or b"").decode("ascii", "replace")
    return (is_end, m.group(2).decode("ascii"),
            "" if actor in ("", "-") else actor)


class LogMonitor:
    """Incremental tailer for one node's worker log directory."""

    def __init__(self, log_dir: str,
                 pid_of: Optional[Callable[[str], Optional[int]]] = None,
                 max_read: int = MAX_READ_PER_SCAN):
        self.log_dir = log_dir
        self._pid_of = pid_of or (lambda _wid: None)
        self._max_read = max_read
        self._offsets: Dict[str, int] = {}
        # Trailing bytes of a file that did not end in a newline yet.
        self._partial: Dict[str, bytes] = {}
        # path -> (task_id_hex, actor_id_hex) currently open in that
        # stream (markers persist across scans).
        self._current_task: Dict[str, Tuple[str, str]] = {}

    def scan(self) -> List[dict]:
        """Collect new complete lines per worker file since the last scan.
        Returns pubsub-ready messages: {worker_id, pid, lines, is_err,
        task_id, actor_id} — one message per contiguous same-task run of
        lines, so attribution survives task switches mid-scan."""
        out: List[dict] = []
        try:
            names = os.listdir(self.log_dir)
        except FileNotFoundError:
            return out
        for name in names:
            m = _FILE_RE.search(name)
            if not m:
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(min(size - offset, self._max_read))
            except OSError:
                continue
            self._offsets[path] = offset + len(data)
            data = self._partial.pop(path, b"") + data
            if not data.endswith(b"\n"):
                data, _, rest = data.rpartition(b"\n")
                if rest:
                    self._partial[path] = rest
                if not data:
                    continue
            wid = m.group(1)
            is_err = m.group(2) == "err"
            pid = self._pid_of(wid)
            # Split the batch into contiguous same-task segments,
            # consuming markers as they pass.
            segment: List[bytes] = []

            def flush_segment():
                if not segment:
                    return
                task, actor = self._current_task.get(path, ("", ""))
                out.append({
                    "worker_id": wid,
                    "pid": pid,
                    "lines": [ln.decode("utf-8", "replace")
                              for ln in segment],
                    "is_err": is_err,
                    "task_id": task or None,
                    "actor_id": actor or None,
                })
                segment.clear()

            for ln in data.split(b"\n"):
                marker = _parse_marker(ln) if ln.startswith(b"::rtpu:") \
                    else None
                if marker is not None:
                    flush_segment()
                    is_end, task, actor = marker
                    if is_end:
                        cur = self._current_task.get(path)
                        if cur is not None and cur[0] == task:
                            self._current_task.pop(path, None)
                    else:
                        self._current_task[path] = (task, actor)
                    continue
                if ln and not any(p.search(ln) for p in _NOISE):
                    segment.append(ln)
            flush_segment()
        return out


def read_task_lines(path: str, task_id_hex: Optional[str] = None,
                    max_lines: int = 0,
                    max_bytes: int = 4 * 1024 * 1024) -> List[str]:
    """Full-file scan with the marker state machine: the lines belonging
    to ``task_id_hex`` (or all non-marker lines when None). Used by the
    raylet's ``get_log`` RPC — log files outlive their workers, so this
    also serves dead workers. ``max_lines`` > 0 keeps only the tail."""
    try:
        fsize = os.path.getsize(path)
        with open(path, "rb") as f:
            if fsize > max_bytes:
                f.seek(fsize - max_bytes)
                f.readline()  # drop the probably-partial first line
            data = f.read(max_bytes)
    except OSError:
        return []
    out: List[str] = []
    current: Optional[str] = None
    for ln in data.split(b"\n"):
        marker = _parse_marker(ln) if ln.startswith(b"::rtpu:") else None
        if marker is not None:
            is_end, task, _actor = marker
            current = None if is_end else task
            continue
        if not ln:
            continue
        if task_id_hex is not None and current != task_id_hex:
            continue
        out.append(ln.decode("utf-8", "replace"))
    if max_lines > 0:
        out = out[-max_lines:]
    return out


def tail_file(path: str, max_lines: int,
              max_bytes: int = 64 * 1024) -> List[str]:
    """Last ``max_lines`` non-marker lines of a log file (raylet-side
    capture at worker exit for death-error enrichment)."""
    return read_task_lines(path, task_id_hex=None, max_lines=max_lines,
                           max_bytes=max_bytes)


def echo_to_driver(message: dict, node_host: str, write) -> None:
    """Driver-side rendering of one pubsub "logs" message (reference
    format: `(pid=…, ip=…) line`; stderr batches marked so tracebacks
    read distinctly from prints). Also renders ERROR-severity cluster
    events the GCS broadcasts on the same channel."""
    event = message.get("cluster_event")
    if event is not None:
        node = (event.get("node_id") or "")[:12]
        write(f"[cluster event] {event.get('severity')} "
              f"{event.get('type')}"
              + (f" (node {node})" if node else "")
              + f": {event.get('message')}\n")
        return
    pid = message.get("pid")
    err = " [stderr]" if message.get("is_err") else ""
    prefix = f"({'pid=' + str(pid) + ', ' if pid else ''}ip={node_host})"
    for line in message.get("lines", ()):
        write(f"{prefix}{err} {line}\n")
