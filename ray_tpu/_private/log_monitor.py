"""Per-node log aggregation: tail worker logs, publish lines to drivers.

Reference: `python/ray/_private/log_monitor.py:103` — a per-node monitor
tails `session_latest/logs/*` and publishes new lines over GCS pubsub;
every driver subscribes and echoes them, which is how a `print` inside a
remote task shows up on the driver's terminal.

Here the monitor runs as an async task inside the raylet (no extra
process): it scans `{session_dir}/logs/worker-*.out`, remembers a byte
offset per file, and publishes batches of complete lines on the "logs"
pubsub channel. Runtime noise (jax backend preload warnings every worker
emits at import) is filtered before publishing.
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Optional

# Lines every spawned worker emits on interpreter start that carry no
# user signal; echoing them once per worker would drown the driver.
_NOISE = [
    re.compile(rb"WARNING:.*xla_bridge.*experimental"),
    re.compile(rb"^\s*$"),
]

_FILE_RE = re.compile(r"worker-([0-9a-f]+)\.out$")

# Per-file, per-scan read cap: a crash-looping task spewing hundreds of MB
# must not block the raylet event loop in one read() or ship a single
# giant pubsub message. The remainder is picked up next scan.
MAX_READ_PER_SCAN = 256 * 1024


class LogMonitor:
    """Incremental tailer for one node's worker log directory."""

    def __init__(self, log_dir: str,
                 pid_of: Optional[Callable[[str], Optional[int]]] = None,
                 max_read: int = MAX_READ_PER_SCAN):
        self.log_dir = log_dir
        self._pid_of = pid_of or (lambda _wid: None)
        self._max_read = max_read
        self._offsets: Dict[str, int] = {}
        # Trailing bytes of a file that did not end in a newline yet.
        self._partial: Dict[str, bytes] = {}

    def scan(self) -> List[dict]:
        """Collect new complete lines per worker file since the last scan.
        Returns pubsub-ready messages: {worker_id, pid, lines}."""
        out: List[dict] = []
        try:
            names = os.listdir(self.log_dir)
        except FileNotFoundError:
            return out
        for name in names:
            m = _FILE_RE.search(name)
            if not m:
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(min(size - offset, self._max_read))
            except OSError:
                continue
            self._offsets[path] = offset + len(data)
            data = self._partial.pop(path, b"") + data
            if not data.endswith(b"\n"):
                data, _, rest = data.rpartition(b"\n")
                if rest:
                    self._partial[path] = rest
                if not data:
                    continue
            lines = [ln for ln in data.split(b"\n")
                     if ln and not any(p.search(ln) for p in _NOISE)]
            if not lines:
                continue
            wid = m.group(1)
            out.append({
                "worker_id": wid,
                "pid": self._pid_of(wid),
                "lines": [ln.decode("utf-8", "replace") for ln in lines],
            })
        return out


def echo_to_driver(message: dict, node_host: str, write) -> None:
    """Driver-side rendering of one pubsub "logs" message (reference
    format: `(pid=…, ip=…) line`)."""
    pid = message.get("pid")
    prefix = f"({'pid=' + str(pid) + ', ' if pid else ''}ip={node_host})"
    for line in message.get("lines", ()):
        write(f"{prefix} {line}\n")
