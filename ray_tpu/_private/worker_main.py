"""Worker process entry point.

Role-equivalent to the reference's `default_worker.py` + `worker.main_loop`
(`_private/worker.py:869`): boot a core worker, register with the local
raylet, then serve task-execution RPCs forever. The process exits when its
raylet kills it, when `kill_self` arrives, or when the raylet connection is
lost (fate-sharing).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import JobID, WorkerID
from ray_tpu._private.worker import MODE_WORKER, Worker, set_global_worker


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-host", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--job-id", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--raylet-pid", type=int, default=0)
    args = parser.parse_args()

    # SIGUSR1 dumps all thread stacks to stderr (the worker log) — a
    # wedged cluster can be post-mortemed by signalling every daemon
    # (gcs_server/raylet register the same handler).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    # SIGUSR2 dumps parked-coroutine stacks + submit-queue state for
    # every event loop — faulthandler can't see awaits (rpc.py).
    from ray_tpu._private.rpc import install_coroutine_dump_signal
    install_coroutine_dump_signal()

    # runtime_env working_dir: the raylet exports it when this worker's
    # pool was spawned for an env that sets one (env_vars arrive directly
    # in this process's environment, applied at spawn).
    wd = os.environ.get("RAY_TPU_WORKING_DIR")
    if wd and os.path.isdir(wd):
        os.chdir(wd)
        sys.path.insert(0, wd)

    worker = Worker(
        mode=MODE_WORKER,
        gcs_addr=(args.gcs_host, args.gcs_port),
        raylet_addr=(args.raylet_host, args.raylet_port),
        node_id=bytes.fromhex(args.node_id),
        job_id=JobID(bytes.fromhex(args.job_id)),
        worker_id=WorkerID(bytes.fromhex(args.worker_id)),
        session_dir=args.session_dir,
    )
    set_global_worker(worker)

    reply = worker.raylet.call(
        "register_worker", worker_id=worker.worker_id.binary(),
        port=worker.port, pid=os.getpid(), job_id=worker.job_id.binary())
    if not reply.get("ok"):
        print("raylet refused worker registration; exiting", file=sys.stderr)
        sys.exit(1)
    GlobalConfig.load_system_config(reply.get("system_config", "{}"))

    # Mirror the driver's import environment so by-reference pickled
    # functions (module-level in driver-local files) resolve here.
    try:
        job_info = worker.gcs.call("get_job_info",
                                   job_id=worker.job_id.binary(), timeout=10)
        if job_info:
            meta = job_info.get("metadata", {})
            for p in meta.get("sys_path", []):
                if p and p not in sys.path:
                    sys.path.append(p)
            cwd = meta.get("cwd")
            # runtime_env working_dir (chdir'd above) takes precedence over
            # mirroring the driver's cwd.
            if cwd and os.path.isdir(cwd) and not wd:
                os.chdir(cwd)
    except Exception:
        pass

    # Fate-share with the raylet. The PRIMARY signal is process
    # liveness (os.kill(pid, 0)) — it cannot false-positive when the
    # raylet is merely busy. RPC pings are only a backstop for a raylet
    # whose process is alive but whose server is permanently wedged,
    # and require a long consecutive-failure streak: a single missed
    # ping used to os._exit(1) here, and under a 500-actor spawn storm
    # after a 1M-task drain the raylet's loop stalls >10s, which
    # mass-suicided whole batches of healthy actor workers (actors
    # DEAD in bursts of ~36 while every node stayed ALIVE).
    def raylet_process_alive(pid: int) -> bool:
        # os.kill(pid, 0) alone treats a ZOMBIE raylet (crashed, not yet
        # reaped by its parent) as alive — read the state field instead.
        try:
            with open(f"/proc/{pid}/stat") as f:
                state = f.read().rsplit(") ", 1)[1].split()[0]
            return state != "Z"
        except OSError:
            return False

    ping_fails = 0
    while True:
        time.sleep(2.0)
        if args.raylet_pid and not raylet_process_alive(args.raylet_pid):
            os._exit(1)  # raylet process is gone (or a zombie)
        try:
            worker.raylet.call("node_stats", timeout=10)
            ping_fails = 0
        except Exception as e:
            # Instant refusal means nothing is listening — the raylet's
            # server is gone even if a pid lingers — so weigh it far
            # heavier than a timeout (a BUSY raylet times out, it does
            # not refuse). The RPC layer wraps ECONNREFUSED in
            # ConnectionLost, so match on the message.
            refused = "refused" in str(e).lower()
            if refused:
                ping_fails += 5
            elif not args.raylet_pid:
                ping_fails += 1
            else:
                # The raylet PROCESS is verifiably alive (liveness check
                # above) and merely too busy to answer in 10s. Weighting
                # these like refusals mass-suicided hundreds of healthy
                # workers during a 10^3-actor storm whose raylet loop
                # stalled 30s+ (respawns then fed the stall) — but a
                # PERMANENTLY wedged-yet-alive server must still
                # fate-share eventually, so timeouts count at 1/10
                # weight: ~60 min of CONSECUTIVE dead air to trip vs
                # ~1 min before.
                ping_fails += 0.1
            if ping_fails >= (30 if args.raylet_pid else 5):
                print(f"raylet unreachable (score {ping_fails}, last: "
                      f"{e}); exiting", file=sys.stderr, flush=True)
                os._exit(1)


if __name__ == "__main__":
    main()
