"""Worker process entry point.

Role-equivalent to the reference's `default_worker.py` + `worker.main_loop`
(`_private/worker.py:869`): boot a core worker, register with the local
raylet, then serve task-execution RPCs forever. The process exits when its
raylet kills it, when `kill_self` arrives, or when the raylet connection is
lost (fate-sharing).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import JobID, WorkerID
from ray_tpu._private.worker import MODE_WORKER, Worker, set_global_worker


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-host", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--job-id", required=True)
    parser.add_argument("--session-dir", required=True)
    args = parser.parse_args()

    # runtime_env working_dir: the raylet exports it when this worker's
    # pool was spawned for an env that sets one (env_vars arrive directly
    # in this process's environment, applied at spawn).
    wd = os.environ.get("RAY_TPU_WORKING_DIR")
    if wd and os.path.isdir(wd):
        os.chdir(wd)
        sys.path.insert(0, wd)

    worker = Worker(
        mode=MODE_WORKER,
        gcs_addr=(args.gcs_host, args.gcs_port),
        raylet_addr=(args.raylet_host, args.raylet_port),
        node_id=bytes.fromhex(args.node_id),
        job_id=JobID(bytes.fromhex(args.job_id)),
        worker_id=WorkerID(bytes.fromhex(args.worker_id)),
        session_dir=args.session_dir,
    )
    set_global_worker(worker)

    reply = worker.raylet.call(
        "register_worker", worker_id=worker.worker_id.binary(),
        port=worker.port, pid=os.getpid(), job_id=worker.job_id.binary())
    if not reply.get("ok"):
        print("raylet refused worker registration; exiting", file=sys.stderr)
        sys.exit(1)
    GlobalConfig.load_system_config(reply.get("system_config", "{}"))

    # Mirror the driver's import environment so by-reference pickled
    # functions (module-level in driver-local files) resolve here.
    try:
        job_info = worker.gcs.call("get_job_info",
                                   job_id=worker.job_id.binary(), timeout=10)
        if job_info:
            meta = job_info.get("metadata", {})
            for p in meta.get("sys_path", []):
                if p and p not in sys.path:
                    sys.path.append(p)
            cwd = meta.get("cwd")
            # runtime_env working_dir (chdir'd above) takes precedence over
            # mirroring the driver's cwd.
            if cwd and os.path.isdir(cwd) and not wd:
                os.chdir(cwd)
    except Exception:
        pass

    # Fate-share with the raylet: if pings start failing, exit.
    while True:
        time.sleep(2.0)
        try:
            worker.raylet.call("node_stats", timeout=10)
        except Exception:
            os._exit(1)


if __name__ == "__main__":
    main()
