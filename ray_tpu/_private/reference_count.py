"""Owner-side reference counting + object directory.

Role-equivalent to the reference's distributed ref counter and
ownership-based object directory (`reference_count.h:61`,
`ownership_based_object_directory.h`): the worker that created an object is
its *owner*; it tracks (a) local Python refs, (b) pending submitted tasks
that depend on the object, (c) whether the ref was serialized out (shared —
conservatively pinned this round in lieu of the full borrower protocol), and
(d) the set of nodes holding a sealed copy. When counts hit zero the object
is freed everywhere via the on_free callback.

Pure, single-threaded-per-owner state machine — tested standalone like
`reference_count_test.cc` does.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set


@dataclass
class _Ref:
    local: int = 0
    task_deps: int = 0
    shared: bool = False
    freed: bool = False
    locations: Set[bytes] = field(default_factory=set)
    is_owned_by_us: bool = True


class ReferenceCounter:
    def __init__(self, on_free: Optional[Callable[[bytes, Set[bytes]], None]] = None):
        self._refs: Dict[bytes, _Ref] = {}
        self._lock = threading.RLock()
        self._on_free = on_free

    # -- ref lifecycle ------------------------------------------------------
    def add_owned(self, object_id: bytes) -> None:
        with self._lock:
            self._refs.setdefault(object_id, _Ref())

    def add_borrowed(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.is_owned_by_us = False

    def add_local_ref(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.local += 1

    def remove_local_ref(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.local = max(0, ref.local - 1)
            self._maybe_free(object_id, ref)

    def add_task_dependency(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.task_deps += 1

    def remove_task_dependency(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.task_deps = max(0, ref.task_deps - 1)
            self._maybe_free(object_id, ref)

    def mark_shared(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.shared = True

    # -- directory ----------------------------------------------------------
    def add_location(self, object_id: bytes, node_id: bytes) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.locations.add(node_id)

    def remove_location(self, object_id: bytes, node_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.locations.discard(node_id)

    def locations(self, object_id: bytes) -> Set[bytes]:
        with self._lock:
            ref = self._refs.get(object_id)
            return set(ref.locations) if ref else set()

    # -- queries ------------------------------------------------------------
    def has_ref(self, object_id: bytes) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref is not None and not ref.freed

    def is_freed(self, object_id: bytes) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref is not None and ref.freed

    def num_tracked(self) -> int:
        with self._lock:
            return sum(1 for r in self._refs.values() if not r.freed)

    def snapshot(self, object_id: bytes) -> Optional[dict]:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return None
            return {"local": ref.local, "task_deps": ref.task_deps,
                    "shared": ref.shared, "freed": ref.freed,
                    "locations": set(ref.locations)}

    # -- freeing ------------------------------------------------------------
    def _maybe_free(self, object_id: bytes, ref: _Ref) -> None:
        if (ref.local == 0 and ref.task_deps == 0 and not ref.shared
                and not ref.freed and ref.is_owned_by_us):
            ref.freed = True
            locations = set(ref.locations)
            ref.locations.clear()
            if self._on_free is not None:
                self._on_free(object_id, locations)

    def force_free(self, object_id: bytes) -> None:
        """Explicit free (`ray_tpu.internal.free`) regardless of counts."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or ref.freed:
                return
            ref.freed = True
            locations = set(ref.locations)
            ref.locations.clear()
            if self._on_free is not None:
                self._on_free(object_id, locations)
