"""Owner-side reference counting + object directory + borrower protocol.

Role-equivalent to the reference's distributed ref counter and
ownership-based object directory (`reference_count.h:61`,
`reference_count.cc`, `ownership_based_object_directory.h`): the worker
that created an object is its *owner*; it tracks (a) local Python refs,
(b) pending submitted tasks that depend on the object, (c) *borrowers* —
remote workers or containing objects that hold the ref after it was
serialized out, and (d) the set of nodes holding a sealed copy. When all
counts drain the object is freed everywhere via the on_free callback.

Borrower protocol (the re-designed analogue of borrowed refs /
WaitForRefRemoved in `reference_count.cc`):

* Serializing a ref out adds a *pending share* — a TTL-stamped pin that
  keeps the object alive while the bytes are in flight to a recipient
  nobody has identified yet.
* A recipient that deserializes the ref registers itself as a borrower
  with the owner (worker-keyed), consuming one pending share. For task
  args this happens before the task body runs, while the caller still
  holds the task-dependency pin — so registration is race-free.
* A ref serialized *inside* another object registers an object-keyed
  borrower (``obj:<outer-id>``) held until the outer object is freed;
  the owner of the outer object releases it (nested refs).
* A borrower whose local refs drain sends release_borrower to the owner
  and drops its entry. Dead borrowers are reaped by the owner's liveness
  sweep; unconsumed pending shares expire after a TTL (config
  ``borrow_pending_ttl_s``) — the backstop that turns every lost-message
  race into a bounded delay instead of a permanent pin (the round-3
  design pinned every serialized-out ref forever).

Pure, lock-guarded state machine — tested standalone like
`reference_count_test.cc` does; all RPC happens in callbacks installed by
the worker.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple


@dataclass(slots=True)
class _Ref:
    local: int = 0
    task_deps: int = 0
    # Monotonic timestamps of serialize-outs not yet claimed by a
    # borrower registration; expired by the TTL sweep.
    pending_shares: List[float] = field(default_factory=list)
    # borrower key -> addr tuple (worker borrowers) or None (object-keyed
    # holders and local-process keys; never pinged).
    borrowers: Dict[bytes, Optional[Tuple[str, int]]] = field(
        default_factory=dict)
    freed: bool = False
    locations: Set[bytes] = field(default_factory=set)
    is_owned_by_us: bool = True
    # Borrower-side bookkeeping: the owner's address (for the release
    # RPC) and whether the release was already emitted.
    owner_addr: Optional[Tuple[str, int]] = None
    released: bool = False


class ReferenceCounter:
    def __init__(
        self,
        on_free: Optional[Callable[[bytes, Set[bytes]], None]] = None,
        on_borrow_release: Optional[
            Callable[[bytes, Tuple[str, int]], None]] = None,
        on_contained_free: Optional[
            Callable[[bytes, List[Tuple[bytes, Optional[Tuple[str, int]]]]],
                     None]] = None,
    ):
        self._refs: Dict[bytes, _Ref] = {}
        # Side index: oids that currently have >=1 pending share. The TTL
        # sweep walks ONLY this set — walking the full _refs table under
        # the lock stalls every add_owned/add_local_ref caller for the
        # whole scan once the table reaches millions of entries (observed
        # as 180 s suite wedges inside add_owned).
        self._with_pending: Set[bytes] = set()
        # Freed-object tombstones: get() distinguishes "freed by owner"
        # from "unknown" via is_freed, but keeping whole _Ref objects for
        # every dead ref grows the heap without bound (a long suite run
        # spent its time in multi-second GC pauses over millions of dead
        # entries). Bounded id set instead.
        self._freed_ids: "OrderedDict[bytes, None]" = OrderedDict()
        self._freed_cap = 200_000
        # outer object id -> [(inner oid, inner owner addr or None=ours)]
        self._contained: Dict[bytes, List[Tuple[bytes, Optional[Tuple]]]] = {}
        self._lock = threading.RLock()
        self._on_free = on_free
        # Borrower side: our last ref on a borrowed object drained — tell
        # the owner at `addr` that we no longer hold `oid`.
        self._on_borrow_release = on_borrow_release
        # Owner side: a freed outer object contained refs owned elsewhere —
        # release our object-keyed borrow with their owners.
        self._on_contained_free = on_contained_free

    # -- ref lifecycle ------------------------------------------------------
    def _live(self, object_id: bytes) -> Optional[_Ref]:
        """Entry for a NOT-freed object, creating if new. None when the
        id is tombstoned — a late-arriving ref copy must never resurrect
        a freed object (that would re-fire on_free and double-release)."""
        if object_id in self._freed_ids:
            return None
        return self._refs.setdefault(object_id, _Ref())

    def add_owned(self, object_id: bytes) -> None:
        with self._lock:
            self._live(object_id)

    def add_borrowed(self, object_id: bytes,
                     owner_addr: Optional[Tuple[str, int]] = None) -> None:
        with self._lock:
            ref = self._live(object_id)
            if ref is None:
                return
            ref.is_owned_by_us = False
            if owner_addr is not None:
                ref.owner_addr = tuple(owner_addr)

    def add_local_ref(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._live(object_id)
            if ref is not None:
                ref.local += 1

    def remove_local_ref(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.local = max(0, ref.local - 1)
            self._maybe_free(object_id, ref)

    def add_task_dependency(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._live(object_id)
            if ref is not None:
                ref.task_deps += 1

    def remove_task_dependency(self, object_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.task_deps = max(0, ref.task_deps - 1)
            self._maybe_free(object_id, ref)

    # -- borrower protocol --------------------------------------------------
    def add_pending_share(self, object_id: bytes) -> None:
        """The ref was serialized out: pin until a recipient registers as
        a borrower or the TTL sweep expires the share."""
        with self._lock:
            ref = self._live(object_id)
            if ref is not None:
                ref.pending_shares.append(time.monotonic())
                self._with_pending.add(object_id)

    # Compatibility alias (round-3 name, thin-client path).
    mark_shared = add_pending_share

    def consume_pending_share(self, object_id: bytes) -> None:
        """The serialized bytes came back to this process (the recipient
        is us): the in-flight pin is no longer needed."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or not ref.pending_shares:
                return
            ref.pending_shares.pop(0)
            if not ref.pending_shares:
                self._with_pending.discard(object_id)
            self._maybe_free(object_id, ref)

    def register_borrower(self, object_id: bytes, key: bytes,
                          addr: Optional[Tuple[str, int]] = None) -> bool:
        """A remote worker (or a containing object) now holds this ref.
        Consumes one pending share. Returns False if the object is
        already freed (late registration — the borrower's ref dangles)."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or ref.freed:
                return False
            if key in ref.borrowers:
                return True  # duplicate registration (RPC retry): no-op
            ref.borrowers[key] = tuple(addr) if addr else None
            if ref.pending_shares:
                ref.pending_shares.pop(0)
                if not ref.pending_shares:
                    self._with_pending.discard(object_id)
            return True

    def release_borrower(self, object_id: bytes, key: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.pop(key, None)
            self._maybe_free(object_id, ref)

    def set_contained(self, outer_id: bytes,
                      inners: List[Tuple[bytes, Optional[Tuple]]]) -> None:
        """Record that the sealed value of `outer_id` embeds `inners`
        (oid, owner_addr-or-None-for-ours). Owner-side holders for inners
        we own must be registered separately (object-keyed borrower)."""
        if not inners:
            return
        with self._lock:
            self._contained.setdefault(outer_id, []).extend(inners)

    def expire_pending(self, ttl_s: float) -> None:
        """Drop pending shares older than ttl_s (lost messages, crashed
        recipients); frees objects whose last pin this was."""
        cutoff = time.monotonic() - ttl_s
        with self._lock:
            candidates = list(self._with_pending)
        # Chunked re-acquire: the sweep must never hold the lock long
        # enough to stall foreground add_owned/add_local_ref callers.
        for i in range(0, len(candidates), 512):
            with self._lock:
                for oid in candidates[i:i + 512]:
                    ref = self._refs.get(oid)
                    if ref is None or not ref.pending_shares:
                        self._with_pending.discard(oid)
                        continue
                    ref.pending_shares = [t for t in ref.pending_shares
                                          if t >= cutoff]
                    if not ref.pending_shares:
                        self._with_pending.discard(oid)
                    self._maybe_free(oid, ref)

    def borrower_addrs(self) -> Dict[Tuple[str, int], List[Tuple[bytes, bytes]]]:
        """addr -> [(object_id, borrower_key)] for every worker-keyed
        borrower; the owner's liveness sweep pings these."""
        out: Dict[Tuple[str, int], List[Tuple[bytes, bytes]]] = {}
        with self._lock:
            for oid, ref in self._refs.items():
                if ref.freed:
                    continue
                for key, addr in ref.borrowers.items():
                    if addr is not None:
                        out.setdefault(addr, []).append((oid, key))
        return out

    # -- directory ----------------------------------------------------------
    def add_location(self, object_id: bytes, node_id: bytes) -> None:
        with self._lock:
            ref = self._live(object_id)
            if ref is not None:
                ref.locations.add(node_id)

    def remove_location(self, object_id: bytes, node_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.locations.discard(node_id)

    def locations(self, object_id: bytes) -> Set[bytes]:
        with self._lock:
            ref = self._refs.get(object_id)
            return set(ref.locations) if ref else set()

    # -- queries ------------------------------------------------------------
    def has_ref(self, object_id: bytes) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref is not None and not ref.freed

    def is_freed(self, object_id: bytes) -> bool:
        with self._lock:
            if object_id in self._freed_ids:
                return True
            ref = self._refs.get(object_id)
            return ref is not None and ref.freed

    def is_borrowed(self, object_id: bytes) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref is not None and not ref.is_owned_by_us

    def num_tracked(self) -> int:
        with self._lock:
            return sum(1 for r in self._refs.values() if not r.freed)

    def snapshot(self, object_id: bytes) -> Optional[dict]:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return None
            return {"local": ref.local, "task_deps": ref.task_deps,
                    "pending_shares": len(ref.pending_shares),
                    "borrowers": set(ref.borrowers),
                    "freed": ref.freed,
                    "locations": set(ref.locations),
                    "is_owned_by_us": ref.is_owned_by_us}

    # -- freeing ------------------------------------------------------------
    def _maybe_free(self, object_id: bytes, ref: _Ref) -> None:
        """Caller must hold the lock."""
        if (ref.local or ref.task_deps or ref.pending_shares
                or ref.borrowers or ref.freed):
            return
        if ref.is_owned_by_us:
            locations = set(ref.locations)
            contained = self._contained.pop(object_id, None)
            self._tombstone(object_id)
            if self._on_free is not None:
                self._on_free(object_id, locations)
            if contained and self._on_contained_free is not None:
                self._on_contained_free(object_id, contained)
        else:
            # Borrower side: our last hold drained — tell the owner once
            # and forget the entry (a re-borrow recreates it).
            if ref.released:
                return
            ref.released = True
            addr = ref.owner_addr
            del self._refs[object_id]
            self._with_pending.discard(object_id)
            if addr is not None and self._on_borrow_release is not None:
                self._on_borrow_release(object_id, addr)

    def drain_borrows(self) -> List[Tuple[bytes, Tuple[str, int]]]:
        """Worker exit: every borrowed entry still alive, for a best-
        effort bulk release."""
        out = []
        with self._lock:
            for oid, ref in list(self._refs.items()):
                if (not ref.is_owned_by_us and not ref.released
                        and ref.owner_addr is not None):
                    ref.released = True
                    out.append((oid, ref.owner_addr))
        return out

    def _tombstone(self, object_id: bytes) -> None:
        """Caller holds the lock: drop the _Ref, remember just the id."""
        self._refs.pop(object_id, None)
        self._with_pending.discard(object_id)
        self._freed_ids[object_id] = None
        while len(self._freed_ids) > self._freed_cap:
            self._freed_ids.popitem(last=False)

    def clear(self) -> None:
        """Worker shutdown: release the whole graph promptly (GC over a
        dead worker's millions of entries otherwise dominates teardown)."""
        with self._lock:
            self._refs.clear()
            self._with_pending.clear()
            self._contained.clear()
            self._freed_ids.clear()

    def force_free(self, object_id: bytes) -> None:
        """Explicit free (`ray_tpu.internal.free`) regardless of counts."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or ref.freed:
                return
            locations = set(ref.locations)
            contained = self._contained.pop(object_id, None)
            self._tombstone(object_id)
            if self._on_free is not None:
                self._on_free(object_id, locations)
            if contained and self._on_contained_free is not None:
                self._on_contained_free(object_id, contained)
