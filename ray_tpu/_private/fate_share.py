"""Daemon fate-sharing with the process that spawned it.

Reference analog: raylet/GCS exit when the session that started them goes
away (for `ray.init()`-started clusters the driver's atexit stops them —
but a SIGKILLed driver strands the daemons). Daemons poll the spawner's
pid and exit when it disappears, so killed test runs never leak a cluster.
"""

from __future__ import annotations

import os
import threading
import time


def watch_parent(pid: int, on_death=None, interval: float = 2.0) -> None:
    """Start a daemon thread that exits this process when `pid` dies."""
    if pid <= 0:
        return

    def _watch():
        while True:
            time.sleep(interval)
            try:
                os.kill(pid, 0)
            except OSError:
                if on_death is not None:
                    try:
                        on_death()
                    except Exception:
                        pass
                os._exit(0)

    threading.Thread(target=_watch, daemon=True,
                     name="fate-share-watch").start()
