"""ctypes binding for the native arena store (native/arena_store.cpp).

The .so builds on first use with the in-image g++ (no pybind11 — plain
C ABI). `load()` returns None when the toolchain is unavailable, and the
store falls back to the file-per-object backend.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libarena_store.so")
_BUILD_LOCK = threading.Lock()
_LIB = None
_LOAD_FAILED = False


def _configure(lib) -> None:
    u64 = ctypes.c_uint64
    lib.rtpu_store_open.restype = ctypes.c_void_p
    lib.rtpu_store_open.argtypes = [ctypes.c_char_p, u64]
    lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_create.restype = u64
    lib.rtpu_store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64]
    lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(u64), ctypes.POINTER(u64)]
    lib.rtpu_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.rtpu_store_addref.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
    lib.rtpu_store_evict.argtypes = [ctypes.c_void_p, u64, ctypes.c_char_p,
                                     u64]
    lib.rtpu_store_lru_pinned.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, u64,
        ctypes.POINTER(u64), ctypes.POINTER(u64)]
    lib.rtpu_store_entry_flags.restype = None
    lib.rtpu_store_entry_flags.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_uint64)]
    lib.rtpu_store_stats.argtypes = [ctypes.c_void_p, u64 * 4]


def load():
    """Build (once) + dlopen the arena store; None if unavailable."""
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        try:
            src = os.path.join(_NATIVE_DIR, "arena_store.cpp")
            if (not os.path.exists(_SO_PATH)
                    or os.path.getmtime(_SO_PATH) < os.path.getmtime(src)):
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO_PATH)
            _configure(lib)
            _LIB = lib
        except Exception:
            _LOAD_FAILED = True
    return _LIB


_UINT64_MAX = 2 ** 64 - 1


class ArenaStore:
    """Thin OO wrapper over the C handle (ids are hex strings)."""

    def __init__(self, arena_path: str, capacity: int):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native arena store unavailable")
        self._h = self._lib.rtpu_store_open(arena_path.encode(), capacity)
        if not self._h:
            raise RuntimeError(f"could not open arena at {arena_path}")
        self.path = arena_path
        self.capacity = capacity

    def create(self, oid: bytes, size: int) -> Optional[int]:
        off = self._lib.rtpu_store_create(self._h, oid.hex().encode(), size)
        return None if off == _UINT64_MAX else off

    def seal(self, oid: bytes) -> bool:
        return self._lib.rtpu_store_seal(self._h, oid.hex().encode()) == 0

    def get(self, oid: bytes) -> Optional[Tuple[int, int]]:
        """(offset, size) of a sealed object, else None."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_store_get(self._h, oid.hex().encode(),
                                      ctypes.byref(off), ctypes.byref(size))
        return (off.value, size.value) if rc == 0 else None

    def contains(self, oid: bytes) -> bool:
        return bool(self._lib.rtpu_store_contains(self._h,
                                                  oid.hex().encode()))

    def delete(self, oid: bytes) -> bool:
        return self._lib.rtpu_store_delete(self._h, oid.hex().encode()) == 0

    def addref(self, oid: bytes, delta: int) -> int:
        return self._lib.rtpu_store_addref(self._h, oid.hex().encode(),
                                           delta)

    def pin(self, oid: bytes, pinned: bool) -> None:
        self._lib.rtpu_store_pin(self._h, oid.hex().encode(),
                                 1 if pinned else 0)

    def evict_for(self, needed: int) -> List[bytes]:
        buf = ctypes.create_string_buffer(64 * 1024)
        n = self._lib.rtpu_store_evict(self._h, needed, buf, len(buf))
        out: List[bytes] = []
        raw = buf.raw
        pos = 0
        for _ in range(n):
            end = raw.index(b"\0", pos)
            if end == pos:
                break
            out.append(bytes.fromhex(raw[pos:end].decode()))
            pos = end + 1
        return out

    def lru_pinned(self) -> Optional[Tuple[bytes, int, int]]:
        buf = ctypes.create_string_buffer(128)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_store_lru_pinned(
            self._h, buf, len(buf), ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return bytes.fromhex(buf.value.decode()), off.value, size.value

    def entry_flags(self, oid: bytes) -> Tuple[int, int, int, int]:
        """(found, sealed, pinned, refs) — debug/diagnostic surface."""
        out = (ctypes.c_uint64 * 4)()
        self._lib.rtpu_store_entry_flags(self._h, oid.hex().encode(), out)
        return tuple(out)

    def stats(self) -> Tuple[int, int, int, int]:
        out = (ctypes.c_uint64 * 4)()
        self._lib.rtpu_store_stats(self._h, out)
        return tuple(out)

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_store_close(self._h)
            self._h = None
