"""Sharding rules for model parameter pytrees.

DP / FSDP / TP / (SP, PP) are mesh-axis annotations over one pjit'd program —
not separate engines (the core TPU-first design decision; contrast the
reference, which delegates TP/PP/FSDP to user libraries — SURVEY §2.7).

GSPMD then derives the collectives: batch sharded over (data, fsdp) gives
gradient psum; params sharded over fsdp gives ZeRO-style all-gather /
reduce-scatter; tensor-axis shards give Megatron-style allreduce — all over
ICI.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.parallel.mesh import mesh_axis_size


def _ax(mesh, name: str) -> Optional[str]:
    """Axis name if present in the mesh with size > 1, else None (replicate)."""
    return name if mesh_axis_size(mesh, name) > 1 else None


def llama_param_specs(config: LlamaConfig, mesh) -> Dict[str, Any]:
    """PartitionSpecs for the stacked Llama param tree.

    Megatron layout on the ``tensor`` axis (attention heads + ffn hidden),
    ZeRO-style on ``fsdp`` (the model dim), replication elsewhere.
    """
    fsdp = _ax(mesh, "fsdp")
    tp = _ax(mesh, "tensor")
    if tp is not None and config.n_kv_heads % mesh_axis_size(mesh, "tensor"):
        raise ValueError(
            f"tensor axis ({mesh_axis_size(mesh, 'tensor')}) must divide "
            f"n_kv_heads ({config.n_kv_heads})")
    ep = _ax(mesh, "expert")
    if ep is not None and config.n_experts \
            and config.n_experts % mesh_axis_size(mesh, "expert"):
        raise ValueError(
            f"expert axis ({mesh_axis_size(mesh, 'expert')}) must divide "
            f"n_experts ({config.n_experts})")
    if config.n_experts:
        # MoE FFN: experts over the "expert" axis (EP), expert-internal
        # dims over tp/fsdp as usual; router tiny -> replicated.
        ffn_specs = {
            "router": P(None, None, None),
            "w_gate": P(None, ep, fsdp, tp),
            "w_up": P(None, ep, fsdp, tp),
            "w_down": P(None, ep, tp, fsdp),
        }
    else:
        ffn_specs = {
            "w_gate": P(None, fsdp, tp),
            "w_up": P(None, fsdp, tp),
            "w_down": P(None, tp, fsdp),
        }
    specs = {
        "embed": P(tp, fsdp),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fsdp, tp),
            "wk": P(None, fsdp, tp),
            "wv": P(None, fsdp, tp),
            "wo": P(None, tp, fsdp),
            "ffn_norm": P(None, None),
            **ffn_specs,
        },
        "norm_f": P(None),
    }
    if not config.tie_embeddings:
        specs["lm_head"] = P(fsdp, tp)
    return specs


def llama_param_shardings(config: LlamaConfig, mesh) -> Dict[str, Any]:
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        llama_param_specs(config, mesh),
                        is_leaf=lambda x: isinstance(x, P))


def batch_spec(mesh) -> P:
    """Global batch sharded over every data-like axis present."""
    axes = [a for a in ("data", "fsdp") if mesh_axis_size(mesh, a) > 1]
    if not axes:
        return P()
    return P(tuple(axes))


def batch_sharding(mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh))


def shard_params(params, shardings):
    """Place (or re-place) a param tree onto its shardings."""
    return jax.tree.map(jax.device_put, params, shardings)


def replicated(mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def context_parallel_attention(mesh, seq_axis: str = "seq",
                               impl: str = "ring"):
    """Attention callable for context-parallel training (SURVEY §7 M11):
    plug into ``LlamaConfig(attn_impl=...)`` / ``forward(attn_impl=...)``
    and the model's attention runs sequence-parallel over
    ``mesh[seq_axis]``. ``impl="ring"`` rotates KV blocks via ppermute;
    ``impl="ulysses"`` all-to-alls into head-sharded full-sequence
    attention (exact, head-count-capped parallelism).
    """
    if impl == "ulysses":
        from ray_tpu.ops.ulysses import ulysses_attention_global as _global
    elif impl == "ring":
        from ray_tpu.ops.ring_attention import (
            ring_attention_global as _global)
    else:
        raise ValueError(f"impl={impl!r}: expected 'ring' or 'ulysses'")

    def attn(q, k, v, causal=True, positions=None):
        return _global(q, k, v, mesh, causal=causal, seq_axis=seq_axis)

    return attn
