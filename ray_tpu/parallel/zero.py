"""ZeRO-style cross-replica sharding of the weight update.

The source-paper lever ("Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training"): in plain data parallelism every replica
allreduces full gradients and then runs an *identical* optimizer update on
an *identical* full copy of the optimizer state — O(model) redundant work
and memory per replica.  Sharding the update converts

    allreduce(grads) ; full Adam          (per replica)
  → reduce-scatter(grads) ; Adam on 1/n   (per replica)
  → allgather(params)

with the same wire bytes as the allreduce (ring RS + ring AG = ring AR)
but 1/n the optimizer FLOPs and 1/n the moment memory per replica.

Two composable routes live here:

1. :func:`build_zero_train_step` — the explicit route.  A `shard_map` step
   over the ``data`` axis where the reduce-scatter / allgather are *our*
   Pallas ring kernels (`ray_tpu.util.collective.pallas`), with the lax
   fallback off-TPU and an optional EQuARX int8 path for the gradient
   exchange.  On a 2-way ring every element is produced by one float add
   in commuted-operand order, so this path is *bitwise* comparable to a
   replicated optax update (tests do exactly that).

2. :func:`zero_state_shardings` + :func:`constrain_opt_state` — the GSPMD
   route, matching the paper's XLA pass.  Composes with the existing pjit
   `build_train_step`: moments get a sharding constraint over the data
   axis, and XLA itself rewrites allreduce+update into
   reduce-scatter + sharded-update + allgather.  Enabled via
   ``build_train_step(..., weight_update="sharded")``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import mesh_axis_size
from ray_tpu.util.collective.pallas import (
    local_quantization_residual, quantized_ring_allreduce, ring_allgather,
    ring_reduce_scatter, start_quantized_ring_reduce_scatter,
    start_ring_allgather, start_ring_reduce_scatter,
    wait_quantized_ring_reduce_scatter, wait_ring_allgather,
    wait_ring_reduce_scatter,
)
from ray_tpu.util.collective.pallas.ring import LANES


class ZeroTrainState(NamedTuple):
    """Replicated params + *sharded* flat optimizer state.

    ``opt_state`` is the optax state over this replica's 1/n shard of the
    flattened parameter vector (moments are (shard_len,) per device).
    ``ef`` is the optional error-feedback accumulator for compressed
    gradient exchange: per-device f32 residual of the last quantization,
    global shape ``(n, padded)`` sharded over the data axis (row i is
    device i's buffer), or None when compression runs without feedback.
    """
    params: Any
    opt_state: Any
    step: jax.Array
    ef: Any = None


def _padded_len(size: int, n: int) -> int:
    group = n * LANES
    return ((size + group - 1) // group) * group


def _flat_shard_len(params, n: int) -> int:
    size = sum(int(jnp.size(l)) for l in jax.tree.leaves(params))
    return _padded_len(size, n) // n


def _pad_flat(flat, n: int):
    padded = _padded_len(flat.size, n)
    if padded != flat.size:
        flat = jnp.pad(flat, (0, padded - flat.size))
    return flat


def _my_shard(flat_padded, n: int, axis_name: str):
    shard = flat_padded.size // n
    my = lax.axis_index(axis_name)
    return lax.dynamic_slice(flat_padded, (my * shard,), (shard,))


def create_zero_state(params, optimizer, mesh, axis_name: str = "data",
                      error_feedback: bool = False) -> ZeroTrainState:
    """Initialize a ZeRO state: params replicated, moments sharded.

    Runs a tiny shard_map so each device initializes the optax state for
    *its* shard only (1/n moment memory from step zero, the whole point).
    With ``error_feedback`` the state also carries a zeroed per-device f32
    residual buffer for compressed-gradient error feedback (always float:
    an int EF buffer would re-quantize the correction itself).
    """
    n = mesh_axis_size(mesh, axis_name)
    shard = _flat_shard_len(params, n)

    def init_shard(flat_padded):
        return optimizer.init(_my_shard(flat_padded, n, axis_name))

    flat, _ = ravel_pytree(params)
    flat = _pad_flat(flat, n)
    opt_shape = jax.eval_shape(lambda f: optimizer.init(f),
                               jax.ShapeDtypeStruct((shard,), flat.dtype))
    out_specs = jax.tree.map(
        lambda l: P(axis_name) if getattr(l, "shape", ()) == (shard,)
        else P(),
        opt_shape)
    from ray_tpu.observability.jit import tracked_jit

    opt_state = tracked_jit(shard_map(
        init_shard, mesh=mesh, in_specs=P(),
        out_specs=out_specs, check_rep=False),
        name="zero_init_shard")(flat)
    ef = None
    if error_feedback:
        ef = jax.device_put(
            jnp.zeros((n, shard * n), jnp.float32),
            NamedSharding(mesh, P(axis_name, None)))
    return ZeroTrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32), ef=ef)


def build_zero_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh,
    axis_name: str = "data",
    batch_spec: Optional[P] = None,
    collective: str = "auto",
    quantized_grads: bool = False,
    overlap: bool = False,
    n_chunks: int = 4,
    error_feedback: bool = False,
) -> Callable[[ZeroTrainState, Any], Tuple[ZeroTrainState, Dict]]:
    """Jitted DP step with a partitioned weight update over `axis_name`.

    Per device: local grads → ring reduce-scatter (sum) → optax update on
    this replica's flat shard → ring allgather of updated params.  With
    ``quantized_grads`` the gradient exchange rides the int8 EQuARX ring;
    the weight allgather stays exact.

    ``overlap=True`` replaces the monolithic exchange with a chunked
    split-phase schedule: the flat vector is cut into ``n_chunks`` chunks
    (boundaries on n*LANES multiples) and pipelined so chunk i+1's
    reduce-scatter hops and chunk i-1's param allgather hops run while
    chunk i's optimizer math executes — communication hides under compute
    instead of serializing with it.  Numerics match the monolithic step to
    float tolerance (per-chunk ring order differs, so not bitwise), and
    the optimizer-state vector uses a chunk-major element order: do not
    toggle ``overlap`` mid-run on the same state.  Requires an elementwise
    optimizer (adam/sgd/etc) since moment vectors are updated per chunk.

    ``error_feedback=True`` (requires ``quantized_grads`` and a state from
    ``create_zero_state(..., error_feedback=True)``) accumulates the local
    quantization residual and re-injects it into the next step's gradient,
    so compressed exchange stops biasing long runs.
    """
    n = mesh_axis_size(mesh, axis_name)
    if batch_spec is None:
        batch_spec = P(axis_name)
    if error_feedback and not quantized_grads:
        raise ValueError(
            "error_feedback corrects compression error and needs "
            "quantized_grads=True (the exact exchange has no residual)")
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")

    def _start_rs(vec):
        c2d = vec.reshape(-1, LANES)
        if quantized_grads:
            return start_quantized_ring_reduce_scatter(
                c2d, axis_name, n=n, impl=collective)
        return start_ring_reduce_scatter(
            c2d, axis_name, n=n, op="sum", impl=collective)

    def _wait_rs(handle):
        if quantized_grads:
            return wait_quantized_ring_reduce_scatter(handle).reshape(-1)
        return wait_ring_reduce_scatter(handle).reshape(-1)

    def _overlap_update(carry, pflat_p, opt_state):
        """The pipelined schedule.  Chunk boundaries sit on n*LANES
        multiples so every chunk reduce-scatters to equal per-device
        slices and the concatenated shards exactly tile the padded
        vector."""
        my = lax.axis_index(axis_name)
        groups = pflat_p.size // (n * LANES)
        n_c = max(1, min(n_chunks, groups))
        base, rem = divmod(groups, n_c)
        sizes = [(base + (1 if i < rem else 0)) * n * LANES
                 for i in range(n_c)]
        offs = [sum(sizes[:i]) for i in range(n_c)]

        leaves, treedef = jax.tree.flatten(opt_state)
        is_vec = [getattr(l, "ndim", 0) == 1 for l in leaves]

        handles = [None] * n_c
        handles[0] = _start_rs(carry[offs[0]:offs[0] + sizes[0]])
        ag_handles = []
        new_chunk_leaves = []
        ef_chunks = []
        opt_off = 0
        for c in range(n_c):
            cs = sizes[c] // n
            if c + 1 < n_c:
                # Issue the next chunk's reduce-scatter before consuming
                # this one: its hops hide under this chunk's update math.
                handles[c + 1] = _start_rs(
                    carry[offs[c + 1]:offs[c + 1] + sizes[c + 1]])
            gshard_c = _wait_rs(handles[c])
            pshard_c = lax.dynamic_slice(
                pflat_p, (offs[c] + my * cs,), (cs,))
            opt_c = jax.tree.unflatten(treedef, [
                l[opt_off:opt_off + cs] if isv else l
                for l, isv in zip(leaves, is_vec)])
            updates_c, new_opt_c = optimizer.update(
                gshard_c, opt_c, pshard_c)
            new_pshard_c = optax.apply_updates(pshard_c, updates_c)
            new_chunk_leaves.append(jax.tree.leaves(new_opt_c))
            # The updated shard leaves immediately: its allgather hops
            # hide under the next chunk's wait + optimizer math.
            ag_handles.append(start_ring_allgather(
                new_pshard_c, axis_name, n=n, impl=collective))
            if error_feedback:
                ef_chunks.append(local_quantization_residual(
                    carry[offs[c]:offs[c] + sizes[c]].reshape(-1, LANES),
                    n).reshape(-1))
            opt_off += cs
        # Scalar leaves (e.g. adam's count) increment identically in every
        # chunk update; keep chunk 0's copy.  Vector leaves concatenate in
        # chunk-major order — the overlap state layout.
        merged = [
            jnp.concatenate([new_chunk_leaves[c][i] for c in range(n_c)])
            if is_vec[i] else new_chunk_leaves[0][i]
            for i in range(len(leaves))]
        new_opt = jax.tree.unflatten(treedef, merged)
        gathered = [wait_ring_allgather(h).reshape(-1)
                    for h in ag_handles]
        new_flat_p = jnp.concatenate(gathered)
        new_ef = (jnp.concatenate(ef_chunks)[None, :]
                  if error_feedback else None)
        return new_flat_p, new_opt, new_ef

    def step_fn(state: ZeroTrainState, batch):
        params, opt_state, step, ef = state
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        gflat, _ = ravel_pytree(grads)
        pflat, unravel = ravel_pytree(params)
        gflat = _pad_flat(gflat, n)
        pflat_p = _pad_flat(pflat, n)
        # Error feedback: re-inject the residual the wire dropped last
        # step, then remember what this step's compression will drop.
        carry = gflat + ef[0] if error_feedback else gflat

        if overlap:
            new_flat_p, new_opt, new_ef = _overlap_update(
                carry, pflat_p, opt_state)
        else:
            c2d = carry.reshape(-1, LANES)
            if quantized_grads:
                gfull = quantized_ring_allreduce(
                    c2d, axis_name, n=n, impl=collective).reshape(-1)
                gshard = _my_shard(gfull, n, axis_name)
            else:
                gshard = ring_reduce_scatter(
                    c2d, axis_name, n=n, op="sum",
                    impl=collective).reshape(-1)
            pshard = _my_shard(pflat_p, n, axis_name)
            updates, new_opt = optimizer.update(gshard, opt_state, pshard)
            new_pshard = optax.apply_updates(pshard, updates)
            gathered = ring_allgather(
                new_pshard.reshape(-1, LANES), axis_name, n=n,
                impl=collective)
            new_flat_p = gathered.reshape(-1)
            new_ef = (local_quantization_residual(c2d, n)
                      .reshape(-1)[None, :] if error_feedback else None)

        if not error_feedback:
            new_ef = ef  # pass any existing buffer through untouched
        new_params = unravel(new_flat_p[:pflat.size])
        grad_norm = jnp.sqrt(lax.psum(jnp.sum(gflat * gflat), axis_name))
        metrics = {"loss": lax.pmean(loss, axis_name),
                   "grad_norm": grad_norm, "step": step + 1}
        return ZeroTrainState(new_params, new_opt, step + 1,
                              new_ef), metrics

    jitted_cache: Dict[Any, Callable] = {}

    def wrapped(state: ZeroTrainState, batch):
        if error_feedback and state.ef is None:
            raise ValueError(
                "error_feedback=True needs a state carrying an ef buffer;"
                " build it with create_zero_state(..., "
                "error_feedback=True)")
        cache_key = (jax.tree.structure(state), jax.tree.structure(batch))
        fn = jitted_cache.get(cache_key)
        if fn is None:
            opt_specs = jax.tree.map(
                lambda l: P(axis_name) if getattr(l, "ndim", 0) == 1
                else P(),
                state.opt_state)
            state_specs = ZeroTrainState(
                params=jax.tree.map(lambda _: P(), state.params),
                opt_state=opt_specs,
                step=P(),
                ef=None if state.ef is None else P(axis_name, None))
            metric_specs = {"loss": P(), "grad_norm": P(), "step": P()}
            batch_specs = jax.tree.map(lambda _: batch_spec, batch)
            from ray_tpu.observability.jit import tracked_jit

            fn = tracked_jit(shard_map(
                step_fn, mesh=mesh,
                in_specs=(state_specs, batch_specs),
                out_specs=(state_specs, metric_specs),
                check_rep=False), name="zero_train_step",
                donate_argnums=(0,))
            jitted_cache[cache_key] = fn
        return fn(state, batch)

    return wrapped


# ---------------------------------------------------------------------------
# GSPMD route: sharding constraints that make XLA perform the same
# rewrite inside the existing pjit train step (the paper's compiler pass,
# expressed as annotations).
# ---------------------------------------------------------------------------

def _shard_leading(spec: P, axis: str, dim0: int, axis_size: int
                   ) -> Optional[P]:
    """Prepend `axis` onto dim 0 of `spec` when legal (dim divisible,
    dim 0 not already sharded)."""
    entries = tuple(spec) if len(tuple(spec)) else (None,)
    if dim0 % axis_size or entries[0] is not None:
        return None
    return P(axis, *entries[1:])


def zero_moment_shardings(param_specs, optimizer, params_shape, mesh,
                          axis_name: str = "data"):
    """Shardings for optimizer moments with the data axis folded in:
    each moment leaf whose param spec leaves dim 0 unsharded (and whose
    dim 0 divides the data-axis size) is additionally sharded over
    `axis_name` — the ZeRO partitioning of optimizer state.

    Returns the opt-state-shaped tree of `NamedSharding | "keep"` ("keep"
    = leave as the mirror-of-params default; a string sentinel because
    None is an empty subtree to pytrees and would break alignment)."""
    axis_size = mesh_axis_size(mesh, axis_name)
    opt_shape = jax.eval_shape(lambda p: optimizer.init(p), params_shape)
    params_td = jax.tree.structure(params_shape)
    param_leaf_shapes = [l.shape for l in jax.tree.leaves(params_shape)]
    spec_leaves = jax.tree.leaves(param_specs,
                                  is_leaf=lambda x: isinstance(x, P))

    def mirrors_params(node) -> bool:
        try:
            if jax.tree.structure(node) != params_td:
                return False
            leaves = jax.tree.leaves(node)
        except Exception:
            return False
        return [getattr(l, "shape", None) for l in leaves] \
            == param_leaf_shapes

    def shard_mirror(node):
        leaves, td = jax.tree.flatten(node)
        out = []
        for leaf, spec in zip(leaves, spec_leaves):
            zspec = _shard_leading(spec, axis_name, leaf.shape[0],
                                   axis_size) if leaf.ndim else None
            out.append(NamedSharding(mesh, zspec) if zspec else "keep")
        return jax.tree.unflatten(td, out)

    return jax.tree.map(
        lambda node: shard_mirror(node) if mirrors_params(node)
        else jax.tree.map(lambda _: "keep", node),
        opt_shape,
        is_leaf=lambda n: mirrors_params(n) or jax.tree.structure(
            n).num_leaves <= 1)


def constrain_opt_state(opt_state, moment_shardings):
    """Apply `lax.with_sharding_constraint` wherever `zero_moment_shardings`
    produced a sharding ("keep" leaves pass through untouched)."""
    return jax.tree.map(
        lambda x, s: lax.with_sharding_constraint(x, s)
        if isinstance(s, NamedSharding) else x,
        opt_state, moment_shardings)
