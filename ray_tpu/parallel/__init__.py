from ray_tpu.parallel.mesh import (
    data_parallel_mesh, discover_devices, fsdp_mesh, make_mesh,
    mesh_axis_size,
)
from ray_tpu.parallel.sharding import (
    batch_sharding, batch_spec, context_parallel_attention,
    llama_param_shardings, llama_param_specs, replicated, shard_params,
)
from ray_tpu.parallel.train_step import (
    TrainState, build_eval_step, build_train_step, create_train_state,
)

__all__ = [
    "make_mesh", "data_parallel_mesh", "discover_devices",
    "fsdp_mesh", "mesh_axis_size",
    "context_parallel_attention",
    "llama_param_specs", "llama_param_shardings", "batch_spec",
    "batch_sharding", "shard_params", "replicated", "TrainState",
    "create_train_state", "build_train_step", "build_eval_step",
]
