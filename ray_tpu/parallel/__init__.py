from ray_tpu.parallel.mesh import (
    data_parallel_mesh, discover_devices, fsdp_mesh, make_mesh,
    mesh_axis_size,
)
from ray_tpu.parallel.sharding import (
    batch_sharding, batch_spec, context_parallel_attention,
    llama_param_shardings, llama_param_specs, replicated, shard_params,
)
from ray_tpu.parallel.train_step import (
    TrainState, build_eval_step, build_train_step, create_train_state,
    state_shardings,
)
from ray_tpu.parallel.zero import (
    ZeroTrainState, build_zero_train_step, constrain_opt_state,
    create_zero_state, zero_moment_shardings,
)

__all__ = [
    "make_mesh", "data_parallel_mesh", "discover_devices",
    "fsdp_mesh", "mesh_axis_size",
    "context_parallel_attention",
    "llama_param_specs", "llama_param_shardings", "batch_spec",
    "batch_sharding", "shard_params", "replicated", "TrainState",
    "create_train_state", "build_train_step", "build_eval_step",
    "state_shardings",
    "ZeroTrainState", "build_zero_train_step", "create_zero_state",
    "zero_moment_shardings", "constrain_opt_state",
]
