"""Device-mesh construction helpers.

The sharding/collective design follows the standard TPU recipe: pick a mesh,
annotate shardings, let XLA (GSPMD) insert the collectives, profile, iterate.
Axes used across ray_tpu:

  data   — pure data parallelism (gradient psum)
  fsdp   — sharded data parallelism (params sharded, ZeRO-equivalent via
           GSPMD all-gather/reduce-scatter)
  tensor — tensor (Megatron-style) parallelism within a layer
  pipe   — pipeline stages
  seq    — sequence/context parallelism (ring attention)

On a TPU slice, order axes so that tensor/seq (highest-bandwidth traffic)
map to contiguous ICI neighbours; data/pipe tolerate DCN.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

AXIS_ORDER = ("data", "fsdp", "pipe", "seq", "tensor")

# Env vars whose presence marks a multi-host launch (TPU pod slice /
# multi-process GPU): a coordinator exists, so the GLOBAL device list is
# only visible after joining jax.distributed.
_COORDINATOR_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
)

_distributed_join_attempted = False


def _multihost_env() -> bool:
    return any(os.environ.get(v) for v in _COORDINATOR_VARS)


def _maybe_join_distributed() -> None:
    """Join the jax.distributed service once, and only when the
    environment says there is one to join.

    Under a multi-host launch, the local backend alone discovers only
    this process's chips — `jax.devices()` then reports e.g. 1 of 8
    devices and every multi-axis mesh request fails its divisibility
    check (MULTICHIP_r05: `1 devices not divisible by 4`). The fix is
    ordering: `jax.distributed.initialize()` must run before the first
    backend touch, after which `jax.devices()` is the global list. On
    single-host setups (no coordinator vars) this is a no-op — tests
    and laptops never pay for or hang on an unreachable coordinator.
    """
    global _distributed_join_attempted
    if _distributed_join_attempted:
        return
    _distributed_join_attempted = True
    if not _multihost_env():
        return
    import jax

    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return                      # someone already joined
    except Exception:
        pass
    try:
        # Coordinator address / process id / num_processes all come from
        # the environment (jax reads the standard vars itself).
        jax.distributed.initialize()
    except Exception:
        # Best effort: a failed join leaves local-only discovery, and
        # make_mesh's inventory message reports the process topology so
        # the failure is diagnosable rather than a bare count mismatch.
        pass


def discover_devices() -> List:
    """The global accelerator inventory: joins `jax.distributed` first
    under multi-host launches so the list spans every process's chips,
    not just the local backend's."""
    import jax

    _maybe_join_distributed()
    return list(jax.devices())


def device_inventory(devices: Optional[Sequence] = None
                     ) -> Dict[str, object]:
    """Structured accelerator inventory: count, platforms, chip
    generation/kind, and the chip-spec peaks the XLA attribution plane
    divides by (observability/chipspec.py). Unknown kinds degrade to
    ``spec: "unknown"`` with no peaks — never fabricated numbers."""
    from ray_tpu.observability import chipspec

    devices = list(devices if devices is not None else discover_devices())
    platforms = sorted({getattr(d, "platform", "?") for d in devices})
    kinds = sorted({str(getattr(d, "device_kind", None)
                        or getattr(d, "platform", "?"))
                    for d in devices})
    # One spec per inventory: heterogeneous kinds degrade to unknown
    # rather than averaging peaks that don't share a roofline.
    if len(kinds) == 1:
        spec = chipspec.lookup(kinds[0])
    else:
        spec = chipspec.UNKNOWN
    return {
        "devices": len(devices),
        "platforms": platforms,
        "device_kinds": kinds,
        "spec": spec.spec,
        "measurement": spec.measurement,
        "peak_flops": spec.peak_flops,
        "peak_hbm_bytes_per_s": spec.peak_hbm_bytes_per_s,
    }


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a Mesh from {axis: size}; one axis may be -1 (absorbs the rest).

    Axis order follows AXIS_ORDER so tensor-parallel neighbours are adjacent
    in the device list (innermost => ICI-contiguous on TPU).
    """
    import jax

    devices = list(devices if devices is not None else discover_devices())
    n = len(devices)

    def _inventory() -> str:
        # "what did JAX actually discover" — the first question every
        # mesh-shape mismatch report needs answered.
        inv = device_inventory(devices)
        platforms = inv["platforms"]
        listing = ", ".join(str(d) for d in devices[:8])
        if n > 8:
            listing += f", ... ({n - 8} more)"
        try:
            topo = (f"; process {jax.process_index()} of "
                    f"{jax.process_count()}")
        except Exception:
            topo = ""
        kinds = "/".join(inv["device_kinds"]) or "none"
        return (f"discovered {n} device(s) on platform "
                f"{'/'.join(platforms) or 'none'} "
                f"(chip {kinds}, spec {inv['spec']}): [{listing}]{topo}")

    sizes = dict(axes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("only one axis may be -1")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n % fixed != 0:
            raise ValueError(
                f"cannot infer axis {wild[0]!r}: {n} devices not "
                f"divisible by the fixed-axis product {fixed} "
                f"(requested {axes}); {_inventory()}")
        sizes[wild[0]] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n} are available; "
            f"{_inventory()}")
    names = [a for a in AXIS_ORDER if a in sizes]
    names += [a for a in sizes if a not in names]
    shape = [sizes[a] for a in names]
    return jax.sharding.Mesh(
        np.array(devices).reshape(shape), tuple(names))


def data_parallel_mesh():
    return make_mesh({"data": -1})


def fsdp_mesh(tensor: int = 1):
    return make_mesh({"fsdp": -1, "tensor": tensor})


def mesh_axis_size(mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1
