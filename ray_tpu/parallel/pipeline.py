"""Pipeline parallelism over a mesh axis (SURVEY §2.7 — absent in the
reference in-repo; net-new, TPU-native).

GPipe-style schedule expressed as pure SPMD: every device along the
"pipe" mesh axis holds ONE stage's parameters (stacked pytree sharded on
the leading axis), activations circulate stage-to-stage with
`jax.lax.ppermute` over ICI, and the M-microbatch loop is a `lax.scan`
of M + P - 1 fixed-shape ticks. No host scheduling, no per-stage
processes — the whole pipeline is one jitted program, differentiable
end-to-end (ppermute has a transpose rule, so `jax.grad` through
`pipeline_apply` yields the reverse-schedule backward pass).

Bubble fraction is the usual (P-1)/(M+P-1): pick M >= 4*P for <20%.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.7 spelling
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, mesh: Mesh,
                   axis: str = "pipe") -> jax.Array:
    """Run `stage_fn` P times (one stage per device along `axis`).

    stage_params: pytree with leaves stacked [P, ...] (stage-major),
        sharded over `axis`.
    x: microbatched input [M, mb, ...], replicated along `axis`.
    Returns [M, mb, ...] outputs (replicated along `axis`).
    """
    n_stages = mesh.shape[axis]

    def spmd(params, xs):
        # Inside shard_map: params = THIS stage's slice [1, ...] and xs
        # the full microbatch stack (replicated).
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        m = xs.shape[0]
        n_ticks = m + n_stages - 1

        def varying(v):
            # New-style shard_map tracks "varying manual axes": the scan
            # carry becomes pipe-varying inside the loop, so the initial
            # value must be marked varying too (no-op data-wise).
            pcast = getattr(jax.lax, "pcast", None)
            if pcast is None:
                return v
            try:
                return pcast(v, (axis,), to="varying")
            except Exception:
                return v

        zero = varying(jnp.zeros_like(xs[0]))
        ys = varying(jnp.zeros_like(xs))

        def tick(carry, t):
            recv, ys = carry
            # Stage 0 ingests microbatch t (while t < M); others take the
            # activation handed over by the previous stage.
            mb_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(stage == 0, xs[mb_idx], recv)
            out = stage_fn(params, inp)
            # Last stage completed microbatch t-(P-1) at tick t.
            done_idx = t - (n_stages - 1)
            is_done = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.maximum(done_idx, 0), 0)
            ys = jnp.where(is_done, updated, ys)
            # Hand the activation to the next stage (ring; last->first
            # carries garbage that stage 0 ignores).
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            recv = jax.lax.ppermute(out, axis, perm)
            return (recv, ys), None

        (recv, ys), _ = jax.lax.scan(tick, (zero, ys),
                                     jnp.arange(n_ticks))
        # Only the last stage holds real outputs; replicate along the
        # pipe axis so the caller sees them everywhere.
        ys = jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys))
        return jax.lax.psum(ys, axis)

    specs = jax.tree.map(
        lambda _: P(axis), stage_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(specs, P()), out_specs=P())(stage_params, x)


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] (B must divide evenly)."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by M={n_microbatches}")
    return x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
