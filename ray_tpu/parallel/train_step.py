"""Sharded train-step builder: one pjit'd SPMD step function.

The whole distributed-training engine is here: loss+grad under jit with
param/batch shardings; GSPMD inserts the data-parallel psum, FSDP
all-gather/reduce-scatter, and TP allreduces over ICI. Buffer donation keeps
params/opt-state in place in HBM (no copy per step).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def create_train_state(params, optimizer) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params),
                      step=jnp.zeros((), jnp.int32))


def state_shardings(param_shardings, optimizer, params_shape, mesh,
                    weight_update: str = "replicated") -> TrainState:
    """Shardings for the full TrainState: opt-state mirrors params (moments
    inherit each param's sharding — automatic ZeRO partitioning of optimizer
    state when fsdp is on).

    ``weight_update="sharded"`` additionally folds the ``data`` axis into
    each moment's dim 0 where divisible (`parallel.zero`), so placement
    matches the sharded-update constraint inside the step and the donated
    buffers never reshard.

    The mapping is STRUCTURAL: any subtree of the optimizer state whose
    pytree structure (and leaf shapes) mirrors the param tree — e.g. Adam's
    mu/nu — takes the param shardings subtree wholesale; everything else
    (step counters, empty states) is replicated.  Keying by leaf shape would
    silently mis-shard two same-shaped params with different PartitionSpecs.
    """
    repl = NamedSharding(mesh, P())
    opt_shape = jax.eval_shape(lambda p: optimizer.init(p), params_shape)
    params_td = jax.tree.structure(params_shape)
    param_leaf_shapes = [leaf.shape for leaf in jax.tree.leaves(params_shape)]

    def mirrors_params(node) -> bool:
        try:
            if jax.tree.structure(node) != params_td:
                return False
            leaves = jax.tree.leaves(node)
        except Exception:
            return False
        return [getattr(l, "shape", None) for l in leaves] == param_leaf_shapes

    opt_sh = jax.tree.map(
        lambda node: param_shardings if mirrors_params(node) else repl,
        opt_shape,
        is_leaf=lambda n: mirrors_params(n) or jax.tree.structure(
            n).num_leaves <= 1)
    if weight_update == "sharded":
        from ray_tpu.parallel.zero import zero_moment_shardings

        param_specs = jax.tree.map(lambda s: s.spec, param_shardings)
        zsh = zero_moment_shardings(param_specs, optimizer, params_shape,
                                    mesh)
        opt_sh = jax.tree.map(
            lambda default, z: z if isinstance(z, NamedSharding)
            else default,
            opt_sh, zsh)
    return TrainState(params=param_shardings, opt_state=opt_sh, step=repl)


def build_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh,
    param_shardings,
    batch_shardings,
    grad_accum: int = 1,
    weight_update: str = "replicated",
    params_shape=None,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict]]:
    """Returns jitted (state, batch) -> (state, metrics).

    ``weight_update="sharded"`` turns on the ZeRO-style partitioned
    optimizer update (`parallel.zero` GSPMD route): sharding constraints
    over the ``data`` axis on the optimizer moments make XLA rewrite
    allreduce(grads)+full-update into reduce-scatter + 1/n-update +
    allgather.  Needs ``params_shape`` (a `jax.eval_shape` of the param
    tree) to size the moment shardings.

    Whether XLA *overlaps* that rewrite's collectives with the update
    math is up to its scheduler; for explicit chunked split-phase
    overlap (and int8/error-feedback gradient exchange) use
    `parallel.zero.build_zero_train_step(..., overlap=True)` on a pure
    data mesh instead."""
    if weight_update not in ("replicated", "sharded"):
        raise ValueError(
            f"weight_update must be 'replicated'|'sharded', got "
            f"{weight_update!r}")
    moment_sh = None
    if weight_update == "sharded":
        if params_shape is None:
            raise ValueError(
                "weight_update='sharded' needs params_shape "
                "(jax.eval_shape of the param tree)")
        from ray_tpu.parallel.zero import zero_moment_shardings

        param_specs = jax.tree.map(lambda s: s.spec, param_shardings)
        moment_sh = zero_moment_shardings(param_specs, optimizer,
                                          params_shape, mesh)

    def _loss_and_grads(params, batch):
        if grad_accum <= 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        def micro(carry, mb):
            loss_acc, grad_acc = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            return (loss_acc + loss,
                    jax.tree.map(jnp.add, grad_acc, grads)), None

        micro_batches = jax.tree.map(
            lambda x: x.reshape(grad_accum, x.shape[0] // grad_accum,
                                *x.shape[1:]), batch)
        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            micro, (jnp.zeros(()), zeros), micro_batches)
        inv = 1.0 / grad_accum
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)

    def step_fn(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        loss, grads = _loss_and_grads(state.params, batch)
        grad_norm = optax.global_norm(grads)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        if moment_sh is not None:
            from ray_tpu.parallel.zero import constrain_opt_state

            new_opt = constrain_opt_state(new_opt, moment_sh)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(new_params, new_opt, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": grad_norm,
                           "step": new_state.step}

    from ray_tpu.observability.jit import tracked_jit

    return tracked_jit(
        step_fn, name="train_step",
        in_shardings=(None, batch_shardings),
        donate_argnums=(0,),
    )


def build_eval_step(loss_fn, mesh, batch_shardings):
    def eval_fn(params, batch):
        return loss_fn(params, batch)

    from ray_tpu.observability.jit import tracked_jit

    return tracked_jit(eval_fn, name="eval_step",
                       in_shardings=(None, batch_shardings))
