"""ray_tpu CLI — cluster lifecycle, state inspection, jobs, metrics.

Reference: `python/ray/scripts/scripts.py` (`ray start/stop/status`),
`python/ray/util/state` CLI (`ray list ...`), and the job CLI
(`dashboard/modules/job/cli.py`). argparse-based (no click in the image).

Usage:
  python -m ray_tpu start --head [--num-cpus N] [--port P] [--block]
  python -m ray_tpu start --address HOST:PORT [--num-cpus N]
  python -m ray_tpu stop
  python -m ray_tpu status [--address HOST:PORT]
  python -m ray_tpu list nodes|actors|workers|jobs|tasks|pgs|objects
  python -m ray_tpu job submit -- <shell entrypoint>
  python -m ray_tpu job status|logs <submission-id>
  python -m ray_tpu job list
  python -m ray_tpu metrics
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Any, List, Optional


def _address(args) -> Optional[str]:
    return (getattr(args, "address", None)
            or os.environ.get("RAY_TPU_ADDRESS"))


def _connect(args):
    import ray_tpu

    addr = _address(args)
    if addr:
        ray_tpu.init(address=addr)
    else:
        raise SystemExit(
            "no cluster address: pass --address or set RAY_TPU_ADDRESS")
    return ray_tpu


def _print_table(rows: List[dict]) -> None:
    if not rows:
        print("(empty)")
        return
    cols = list(rows[0].keys())
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    print("  ".join("-" * widths[c] for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


# ----------------------------------------------------------------- commands

def cmd_start(args) -> None:
    from ray_tpu._private.node import Node

    if args.head:
        node = Node(head=True, num_cpus=args.num_cpus,
                    num_tpus=args.num_tpus, fate_share=False,
                    gcs_port=args.port or 0,
                    include_dashboard=not getattr(
                        args, "no_dashboard", False))
        addr = "%s:%d" % node.gcs_addr
        print(f"started head node; cluster address: {addr}")
        print(f"session dir: {node.session_dir}")
        if node.dashboard_url:
            print(f"dashboard: {node.dashboard_url}")
        print(f"  export RAY_TPU_ADDRESS={addr}")
    else:
        addr = _address(args)
        if not addr:
            raise SystemExit("start requires --head or --address")
        host, port = addr.rsplit(":", 1)
        resources = (json.loads(args.resources)
                     if getattr(args, "resources", None) else None)
        labels = (json.loads(args.labels)
                  if getattr(args, "labels", None) else None)
        node = Node(head=False, gcs_addr=(host, int(port)),
                    num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                    resources=resources, labels=labels,
                    fate_share=False)
        print(f"joined cluster at {addr} as node {node.node_id.hex()[:12]}")
    if args.block:
        try:
            signal.pause()
        except KeyboardInterrupt:
            pass
        finally:
            node.shutdown()


def cmd_stop(args) -> None:
    import subprocess

    out = subprocess.run(
        ["pkill", "-f", "ray_tpu._private.(gcs_server|raylet|worker_main)"],
        capture_output=True)
    print("stopped" if out.returncode == 0 else "no daemons found")


# --------------------------------------------------------- cluster launcher

def _launcher_state_path(cluster_name: str) -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f"ray_tpu-cluster-{cluster_name}.json")


def cmd_up(args) -> None:
    """Launch a cluster from a YAML config and run the autoscaler monitor
    (reference: `ray up` — `autoscaler/_private/commands.py:create_or_update_cluster`)."""
    from ray_tpu._private.node import Node
    from ray_tpu.autoscaler.config import load_cluster_config
    from ray_tpu.autoscaler.pod_autoscaler import run_monitor_loop

    cfg = load_cluster_config(args.config)
    head_type = cfg.get("head_node_type")
    head_res = {}
    if head_type:
        head_res = dict(
            cfg["available_node_types"][head_type].get("resources", {}))
    node = Node(head=True, num_cpus=int(head_res.pop("CPU", args.num_cpus)),
                num_tpus=int(head_res.pop("TPU", 0)), resources=head_res,
                fate_share=False)
    addr = "%s:%d" % node.gcs_addr
    state = {"cluster_name": cfg["cluster_name"], "address": addr,
             "session_dir": node.session_dir, "config": args.config,
             "head_pid": os.getpid()}
    with open(_launcher_state_path(cfg["cluster_name"]), "w") as f:
        json.dump(state, f)
    print(f"cluster '{cfg['cluster_name']}' is up; address: {addr}")
    print(f"  attach with: python -m ray_tpu attach {args.config}")
    print(f"  export RAY_TPU_ADDRESS={addr}")
    stop = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *a: stop.update(flag=True))
    try:
        run_monitor_loop(node.gcs_addr, cfg, node.session_dir,
                         stop_check=lambda: stop["flag"])
    except KeyboardInterrupt:
        pass
    finally:
        node.shutdown()
        try:
            os.unlink(_launcher_state_path(cfg["cluster_name"]))
        except OSError:
            pass


def cmd_down(args) -> None:
    """Tear down a launched cluster (reference: `ray down`)."""
    from ray_tpu.autoscaler.config import load_cluster_config

    cfg = load_cluster_config(args.config)
    path = _launcher_state_path(cfg["cluster_name"])
    if not os.path.exists(path):
        raise SystemExit(f"no running cluster '{cfg['cluster_name']}' found")
    with open(path) as f:
        state = json.load(f)
    try:
        os.kill(state["head_pid"], signal.SIGTERM)
        print(f"cluster '{cfg['cluster_name']}' shutting down "
              f"(head pid {state['head_pid']})")
    except ProcessLookupError:
        print("head process already gone; cleaning up state")
    try:
        os.unlink(path)
    except OSError:
        pass


def cmd_attach(args) -> None:
    """Open a Python REPL connected to the launched cluster
    (reference: `ray attach` opens a shell on the head)."""
    from ray_tpu.autoscaler.config import load_cluster_config

    cfg = load_cluster_config(args.config)
    path = _launcher_state_path(cfg["cluster_name"])
    if not os.path.exists(path):
        raise SystemExit(f"no running cluster '{cfg['cluster_name']}' found")
    with open(path) as f:
        state = json.load(f)
    if args.print_address:
        print(state["address"])
        return
    import code

    import ray_tpu

    ray_tpu.init(address=state["address"])
    banner = (f"Attached to cluster '{cfg['cluster_name']}' at "
              f"{state['address']}.\nray_tpu is initialized — e.g. "
              "ray_tpu.cluster_resources()")
    code.interact(banner=banner, local={"ray_tpu": ray_tpu})


def cmd_status(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu.util import state

    s = state.summary()
    print(f"nodes: {s['nodes_alive']} alive / {s['nodes_dead']} dead")
    print(f"actors: {s['actors']}   workers: {s['workers']}")
    print("resources:")
    total, avail = s["cluster_resources"], s["available_resources"]
    for key in sorted(total):
        print(f"  {avail.get(key, 0):.1f}/{total[key]:.1f} {key}")
    ray_tpu.shutdown()


def cmd_list(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu.util import state

    kind = args.kind
    fns = {
        "nodes": state.list_nodes, "actors": state.list_actors,
        "workers": state.list_workers, "jobs": state.list_jobs,
        "tasks": state.list_tasks, "pgs": state.list_placement_groups,
        "placement-groups": state.list_placement_groups,
        "objects": state.list_objects,
    }
    rows = fns[kind]()
    if args.json:
        print(json.dumps(rows, default=str, indent=2))
    else:
        _print_table([{k: v for k, v in r.items()
                       if not isinstance(v, (dict, list))} for r in rows])
    ray_tpu.shutdown()


def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    # http:// address = dashboard job REST API (off-cluster submission,
    # no driver connection needed); otherwise connect as a driver.
    addr = getattr(args, "address", None) or os.environ.get(
        "RAY_TPU_ADDRESS", "")
    if addr.startswith("http"):
        ray_tpu = None
        client = JobSubmissionClient(address=addr)
    else:
        ray_tpu = _connect(args)
        client = JobSubmissionClient()
    if args.job_cmd == "submit":
        parts = list(args.entrypoint)
        if parts and parts[0] == "--":
            parts = parts[1:]
        entrypoint = " ".join(parts)
        sid = client.submit_job(entrypoint=entrypoint,
                                working_dir=args.working_dir)
        print(f"submitted: {sid}")
        if args.wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(f"{sid}: {status}")
            print(client.get_job_logs(sid))
            if status != "SUCCEEDED":
                sys.exit(1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id))
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.submission_id)
              else "not running")
    elif args.job_cmd == "list":
        _print_table(client.list_jobs())
    if ray_tpu is not None:
        ray_tpu.shutdown()


def cmd_metrics(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu._private.worker import global_worker

    print(global_worker().gcs.call("metrics_text", timeout=30), end="")
    ray_tpu.shutdown()


def cmd_serve(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        names = serve.deploy_config_file(args.config)
        print(f"deployed applications: {', '.join(names)}")
    elif args.serve_cmd == "run":
        app = serve.import_application(args.import_path)
        serve.run(app, name=args.name,
                  route_prefix=args.route_prefix)
        print(f"application '{args.name}' running "
              f"(ingress: {args.import_path})")
    elif args.serve_cmd == "status":
        apps = serve.list_applications()
        if not apps:
            print("serve is not running (no applications deployed)")
            ray_tpu.shutdown()
            return
        rows = []
        for app in apps:
            for d in serve.status(app):
                rows.append({"app": app, **d})
        _print_table(rows)
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")
    ray_tpu.shutdown()


def cmd_summary(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu.util import state

    rows = (state.summary_tasks() if args.kind == "tasks"
            else state.summary_actors())
    _print_table(rows)
    ray_tpu.shutdown()


def cmd_timeline(args) -> None:
    ray_tpu = _connect(args)
    trace = ray_tpu.timeline(filename=args.output)
    print(f"wrote {len(trace)} trace events to {args.output} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")
    ray_tpu.shutdown()


# --------------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="ray_tpu")
    parser.add_argument("--address", default=None,
                        help="cluster address HOST:PORT "
                             "(default: $RAY_TPU_ADDRESS)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    # SUPPRESS: absent here must not clobber a globally-passed
    # `ray_tpu --address X start` (subparser defaults overwrite the
    # shared namespace).
    p.add_argument("--address", default=argparse.SUPPRESS,
                   help="cluster GCS address to join (worker mode)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--no-dashboard", action="store_true",
                   help="skip starting the dashboard head")
    p.add_argument("--resources", default=None,
                   help="JSON custom resources for this node, e.g. "
                        "'{\"CPU\": 8, \"TPU\": 4}'")
    p.add_argument("--labels", default=None,
                   help="JSON node labels (the cloud provider tags joined "
                        "nodes with their provider group this way)")
    p.add_argument("--block", action="store_true",
                   help="stay attached; Ctrl-C stops the node")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all local daemons")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("up", help="launch a cluster from a YAML config "
                                  "and run its autoscaler")
    p.add_argument("config")
    p.add_argument("--num-cpus", type=int, default=os.cpu_count() or 1)
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down a launched cluster")
    p.add_argument("config")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("attach", help="REPL attached to a launched cluster")
    p.add_argument("config")
    p.add_argument("--print-address", action="store_true",
                   help="print the cluster address and exit")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("status", help="cluster summary")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=["nodes", "actors", "workers", "jobs",
                                    "tasks", "pgs", "placement-groups",
                                    "objects"])
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("job", help="job submission")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    ps = jsub.add_parser("submit")
    ps.add_argument("--working-dir", default=None)
    ps.add_argument("--wait", action="store_true")
    ps.add_argument("--timeout", type=float, default=600.0)
    ps.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="shell entrypoint (after --)")
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("submission_id")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("metrics", help="prometheus metrics text")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("timeline", help="dump chrome-trace task timeline")
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("summary", help="state rollups")
    p.add_argument("kind", choices=["tasks", "actors"])
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("serve", help="model-serving control")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    pd = ssub.add_parser("deploy", help="deploy a YAML config file")
    pd.add_argument("config")
    pr = ssub.add_parser("run", help="run an app by import path")
    pr.add_argument("import_path", help="module.sub:app")
    pr.add_argument("--name", default="default")
    pr.add_argument("--route-prefix", default=None)
    ssub.add_parser("status")
    ssub.add_parser("shutdown")
    p.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
