"""Microbenchmark CLI (reference: `python/ray/_private/ray_perf.py:120-241`
— `ray microbenchmark`). Named suites, one result line each:

  python -m ray_tpu.scripts.perf [--suite NAME] [--backend native|files]

Suites: tasks (roundtrips/s), actor_calls (sync 1:1 calls/s), put_small
(1 KiB puts/s), put_large + get_large (10 MiB GB/s), wait_many
(ray.wait over 1k inlined refs).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _timeit(fn, n: int) -> float:
    start = time.perf_counter()
    fn()
    return n / (time.perf_counter() - start)


def suite_tasks(ray_tpu, n=200):
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get(nop.remote(), timeout=60)  # warm the pool

    def run():
        ray_tpu.get([nop.remote() for _ in range(n)], timeout=120)

    return "tasks_per_s", _timeit(run, n)


def suite_actor_calls(ray_tpu, n=500):
    @ray_tpu.remote
    class A:
        def nop(self):
            return None

    a = A.remote()
    ray_tpu.get(a.nop.remote(), timeout=60)

    def run():
        ray_tpu.get([a.nop.remote() for _ in range(n)], timeout=120)

    rate = _timeit(run, n)
    ray_tpu.kill(a)
    return "actor_calls_per_s", rate


def suite_put_small(ray_tpu, n=500):
    # Above the inline threshold so every put hits the node store.
    payload = np.zeros(128 * 1024 // 8)

    def run():
        refs = [ray_tpu.put(payload) for _ in range(n)]
        del refs

    return "store_puts_per_s_128k", _timeit(run, n)


def suite_put_large(ray_tpu, n=20):
    payload = np.zeros(10 * 1024 * 1024 // 8)  # 10 MiB

    def run():
        refs = [ray_tpu.put(payload) for _ in range(n)]
        del refs

    rate = _timeit(run, n)
    return "store_put_gb_per_s", rate * 10 / 1024


def suite_get_large(ray_tpu, n=50):
    payload = np.zeros(10 * 1024 * 1024 // 8)
    ref = ray_tpu.put(payload)
    ray_tpu.get(ref, timeout=60)

    from ray_tpu._private.worker import global_worker

    w = global_worker()

    def run():
        for _ in range(n):
            # Drop the client mapping cache so each get pays the full path.
            w._mapped.pop(ref.binary(), None)
            ray_tpu.get(ref, timeout=60)

    rate = _timeit(run, n)
    return "store_get_gb_per_s", rate * 10 / 1024


def suite_wait_many(ray_tpu, n=1000):
    refs = [ray_tpu.put(i) for i in range(n)]

    def run():
        ready, rest = ray_tpu.wait(refs, num_returns=n, timeout=60)
        assert len(ready) == n

    return "wait_1k_refs_per_s", _timeit(run, n)


SUITES = {
    "tasks": suite_tasks,
    "actor_calls": suite_actor_calls,
    "put_small": suite_put_small,
    "put_large": suite_put_large,
    "get_large": suite_get_large,
    "wait_many": suite_wait_many,
}


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu.perf")
    parser.add_argument("--suite", choices=sorted(SUITES), default=None)
    parser.add_argument("--backend", choices=["native", "files"],
                        default=None)
    parser.add_argument("--num-cpus", type=int, default=4)
    args = parser.parse_args(argv)

    if args.backend:
        os.environ["RAY_TPU_object_store_backend"] = args.backend

    import ray_tpu

    ray_tpu.init(num_cpus=args.num_cpus, num_tpus=0,
                 object_store_memory=512 * 1024 * 1024)
    try:
        from ray_tpu._private.worker import global_worker

        backend = global_worker().raylet.call(
            "node_stats", timeout=15)["store"].get("backend")
        names = [args.suite] if args.suite else sorted(SUITES)
        for name in names:
            metric, value = SUITES[name](ray_tpu)
            print(json.dumps({"suite": name, "metric": metric,
                              "value": round(value, 2),
                              "store_backend": backend}))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
