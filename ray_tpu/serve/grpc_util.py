"""Client helpers for the serve gRPC ingress (reference:
`serve/_private/grpc_util.py`). See `_private/grpc_proxy.py` for the
service contract — generic bytes methods with app/method selection in
invocation metadata."""

from __future__ import annotations

import json
from typing import Any, Iterator, Optional

from ray_tpu.serve._private.grpc_proxy import PREDICT, PREDICT_STREAM


class ServeGrpcClient:
    """Thin convenience wrapper over a grpc channel."""

    def __init__(self, target: str):
        import grpc

        self._channel = grpc.insecure_channel(target)
        self._predict = self._channel.unary_unary(PREDICT)
        self._predict_stream = self._channel.unary_stream(PREDICT_STREAM)

    @staticmethod
    def _metadata(application: str, method: str, model_id: Optional[str]):
        md = [("application", application), ("method", method)]
        if model_id:
            md.append(("multiplexed_model_id", model_id))
        return md

    @staticmethod
    def _encode(payload: Any) -> bytes:
        if payload is None:
            return b""
        if isinstance(payload, bytes):
            return payload
        return json.dumps(payload).encode()

    def predict(self, payload: Any = None, *, application: str = "default",
                method: str = "__call__", model_id: Optional[str] = None,
                timeout: float = 120.0) -> bytes:
        return self._predict(
            self._encode(payload), timeout=timeout,
            metadata=self._metadata(application, method, model_id))

    def predict_stream(self, payload: Any = None, *,
                       application: str = "default",
                       method: str = "__call__",
                       timeout: float = 120.0) -> Iterator[bytes]:
        return self._predict_stream(
            self._encode(payload), timeout=timeout,
            metadata=self._metadata(application, method, None))

    def close(self) -> None:
        self._channel.close()
