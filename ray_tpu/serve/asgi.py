"""ASGI integration for serve — FastAPI-style apps as ingress deployments.

Reference: `serve/_private/http_util.py` (ASGIAppReplicaWrapper wraps a
FastAPI/Starlette app inside a replica; the proxy forwards raw HTTP
scope). Re-designed here without a framework dependency:

* ``App`` is a tiny real ASGI application — decorator routing with
  ``{param}`` path templates, query/body parsing, JSON responses. Any
  genuine ASGI app (FastAPI, Starlette) plugs into the same wrapper,
  since the contract is plain ``(scope, receive, send)``.
* ``@serve.ingress(app)`` attaches the ASGI app to a deployment class:
  the proxy forwards the request (method/path/headers/query/body) to the
  replica, which drives the app on a private event loop and returns
  status/headers/body for the proxy to write through.
"""

from __future__ import annotations

import asyncio
import json
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["App", "Request", "Response", "ingress", "run_asgi_request"]


class Request:
    """Handler-facing request view (subset of the usual ASGI toolkits)."""

    def __init__(self, scope: dict, body: bytes):
        self.scope = scope
        self.method: str = scope.get("method", "GET")
        self.path: str = scope.get("path", "/")
        self.path_params: Dict[str, str] = scope.get("path_params", {})
        # Full fidelity list (duplicates preserved) + a convenience dict
        # that joins duplicates per RFC 9110 ("," separated).
        self.header_list: List[Tuple[str, str]] = [
            (k.decode() if isinstance(k, bytes) else k,
             v.decode() if isinstance(v, bytes) else v)
            for k, v in scope.get("headers", [])]
        self.headers = {}
        for k, v in self.header_list:
            self.headers[k] = f"{self.headers[k]}, {v}" \
                if k in self.headers else v
        qs = scope.get("query_string", b"")
        if isinstance(qs, str):
            qs = qs.encode()
        # parse_qsl percent-decodes and handles '+' (ADVICE r4 low — the
        # old hand-split passed values through still encoded).
        from urllib.parse import parse_qsl

        self.query_params_list: List[Tuple[str, str]] = parse_qsl(
            qs.decode(), keep_blank_values=True)
        self.query_params: Dict[str, str] = dict(self.query_params_list)
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return json.loads(self._body or b"null")


class Response:
    def __init__(self, content: Any = b"", status: int = 200,
                 headers=None, media_type: Optional[str] = None):
        if isinstance(content, bytes):
            body = content
            media_type = media_type or "application/octet-stream"
        elif isinstance(content, str):
            body = content.encode()
            media_type = media_type or "text/plain; charset=utf-8"
        else:
            body = json.dumps(content).encode()
            media_type = media_type or "application/json"
        self.body = body
        self.status = status
        # ``headers`` may be any mapping or a list of pairs (the latter
        # emits duplicates, e.g. multiple Set-Cookie).
        pairs = (list(headers.items()) if hasattr(headers, "items")
                 else list(headers or []))
        if not any(k.lower() == "content-type" for k, _ in pairs):
            pairs.append(("content-type", media_type))
        self.header_list: List[Tuple[str, str]] = pairs
        self.headers: Dict[str, str] = dict(pairs)


_PARAM = re.compile(r"{([a-zA-Z_][a-zA-Z0-9_]*)}")


class App:
    """Minimal ASGI application with FastAPI-style decorator routing."""

    def __init__(self):
        # (method, regex, param names, handler)
        self._routes: List[Tuple[str, "re.Pattern", List[str], Callable]] = []

    def route(self, path: str, methods=("GET",)):
        names = _PARAM.findall(path)
        # Escape the literal segments; only {param} placeholders become
        # groups (a '.' or '+' in a route must match itself, not regex).
        src = path.rstrip("/") or "/"
        parts = []
        last = 0
        for m in _PARAM.finditer(src):
            parts.append(re.escape(src[last:m.start()]))
            parts.append(f"(?P<{m.group(1)}>[^/]+)")
            last = m.end()
        parts.append(re.escape(src[last:]))
        pattern = re.compile("^" + "".join(parts) + "/?$")

        def decorator(fn):
            for m in methods:
                self._routes.append((m.upper(), pattern, names, fn))
            return fn
        return decorator

    def get(self, path: str):
        return self.route(path, ("GET",))

    def post(self, path: str):
        return self.route(path, ("POST",))

    def put(self, path: str):
        return self.route(path, ("PUT",))

    def delete(self, path: str):
        return self.route(path, ("DELETE",))

    # ---- the actual ASGI interface ---------------------------------------
    async def __call__(self, scope, receive, send):
        assert scope["type"] == "http"
        body = b""
        while True:
            message = await receive()
            body += message.get("body", b"")
            if not message.get("more_body"):
                break
        method = scope.get("method", "GET")
        path = scope.get("path", "/") or "/"
        for m, pattern, _names, fn in self._routes:
            if m != method:
                continue
            match = pattern.match(path)
            if not match:
                continue
            scope = dict(scope)
            scope["path_params"] = match.groupdict()
            request = Request(scope, body)
            try:
                out = fn(request)
                if asyncio.iscoroutine(out):
                    out = await out
            except Exception as e:  # noqa: BLE001 — app error -> 500
                out = Response({"error": f"{type(e).__name__}: {e}"},
                               status=500)
            resp = out if isinstance(out, Response) else Response(out)
            await _send_response(send, resp)
            return
        await _send_response(
            send, Response({"error": f"no route for {method} {path}"},
                           status=404))


async def _send_response(send, resp: Response) -> None:
    pairs = getattr(resp, "header_list", None) or list(resp.headers.items())
    await send({"type": "http.response.start", "status": resp.status,
                "headers": [(k.encode(), v.encode()) for k, v in pairs]})
    await send({"type": "http.response.body", "body": resp.body})


# ---- replica-side driver ---------------------------------------------------

def run_asgi_request(asgi_app, request: Dict[str, Any],
                     loop: Optional[asyncio.AbstractEventLoop] = None
                     ) -> Dict[str, Any]:
    """Drive one request through an ASGI app and collect the response.

    ``request``: {"method", "path", "query_string", "headers", "body"} as
    forwarded by the proxy. Returns {"status", "headers", "body"}.
    """
    scope = {
        "type": "http",
        "asgi": {"version": "3.0"},
        "http_version": "1.1",
        "method": request.get("method", "GET"),
        "path": request.get("path", "/") or "/",
        "raw_path": (request.get("path", "/") or "/").encode(),
        "query_string": (request.get("query_string") or "").encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (
                        request["headers"].items()
                        if isinstance(request.get("headers"), dict)
                        else (request.get("headers") or []))],
    }
    body = request.get("body") or b""
    if isinstance(body, str):
        body = body.encode()
    sent = {"body": False}

    async def receive():
        if sent["body"]:
            return {"type": "http.disconnect"}
        sent["body"] = True
        return {"type": "http.request", "body": body, "more_body": False}

    out = {"status": 500, "headers": {}, "body": b""}
    chunks: List[bytes] = []

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = message["status"]
            pairs = [((k.decode() if isinstance(k, bytes) else k),
                      (v.decode() if isinstance(v, bytes) else v))
                     for k, v in message.get("headers", [])]
            # header_list keeps duplicates (Set-Cookie); the dict is the
            # backward-compatible view (last value wins).
            out["header_list"] = pairs
            out["headers"] = dict(pairs)
        elif message["type"] == "http.response.body":
            chunks.append(message.get("body", b""))

    async def _drive():
        await asgi_app(scope, receive, send)

    if loop is not None:
        asyncio.run_coroutine_threadsafe(_drive(), loop).result(timeout=120)
    else:
        asyncio.run(_drive())
    out["body"] = b"".join(chunks)
    return out


class _IngressLoop:
    """One persistent event loop per replica process for ASGI dispatch."""

    _lock = threading.Lock()
    _loop: Optional[asyncio.AbstractEventLoop] = None

    @classmethod
    def get(cls) -> asyncio.AbstractEventLoop:
        with cls._lock:
            if cls._loop is None or cls._loop.is_closed():
                loop = asyncio.new_event_loop()
                threading.Thread(target=loop.run_forever, daemon=True,
                                 name="serve-asgi").start()
                cls._loop = loop
            return cls._loop


def ingress(asgi_app):
    """Class decorator binding an ASGI app to a deployment (reference:
    `@serve.ingress(fastapi_app)`): HTTP requests hitting the app's route
    prefix run through the ASGI app inside the replica. The deployment
    instance is exposed to handlers as ``request.scope["deployment"]``."""

    def decorator(cls):
        class ASGIIngress(cls):
            _serve_asgi_app = asgi_app

            def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
                app = self._serve_asgi_app

                async def _with_self(scope, receive, send):
                    scope = dict(scope)
                    scope["deployment"] = self
                    await app(scope, receive, send)

                return run_asgi_request(_with_self, request or {},
                                        loop=_IngressLoop.get())

        ASGIIngress.__name__ = getattr(cls, "__name__", "ASGIIngress")
        ASGIIngress.__qualname__ = ASGIIngress.__name__
        ASGIIngress._serve_is_asgi = True
        return ASGIIngress

    return decorator
