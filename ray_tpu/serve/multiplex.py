"""Model multiplexing (reference: `serve/multiplex.py` +
`serve/api.py` `get_multiplexed_model_id`).

One deployment serves MANY models: each replica lazily loads models on
demand and keeps an LRU of at most `max_num_models_per_replica` (TPU
HBM is the budget). Requests carry a model id
(`handle.options(multiplexed_model_id=...)`); the router sends a given
model id to a stable replica (rendezvous hashing) so each model's weights
load on one replica instead of everywhere.
"""

from __future__ import annotations

import contextvars
import functools
import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Optional

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the request being handled."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id or "")


def _reset_model_id(token) -> None:
    _model_id_ctx.reset(token)


class _MultiplexWrapper:
    """Per-instance LRU of loaded models around a user loader method."""

    def __init__(self, fn: Callable, max_models: int):
        self._fn = fn
        self._max = max_models
        self._per_instance: dict = {}
        self._lock = threading.Lock()
        functools.update_wrapper(self, fn)

    def __reduce__(self):
        # Ships to replicas inside the deployment class; the LRU and lock
        # are process-local and rebuild empty on the other side.
        return (_MultiplexWrapper, (self._fn, self._max))

    def _state(self, instance):
        key = id(instance)
        with self._lock:
            st = self._per_instance.get(key)
            if st is None:
                st = self._per_instance[key] = {
                    "models": OrderedDict(), "lock": threading.Lock(),
                    "loading": {}}
            return st

    def __get__(self, instance, owner=None):
        if instance is None:
            return self

        def bound(model_id: str):
            st = self._state(instance)
            while True:
                with st["lock"]:
                    models = st["models"]
                    if model_id in models:
                        models.move_to_end(model_id)
                        return models[model_id]
                    pending = st["loading"].get(model_id)
                    if pending is None:
                        # We load; others wait (single-flight: a multi-GB
                        # weight load must not run once per concurrent
                        # request).
                        pending = st["loading"][model_id] = threading.Event()
                        loader = True
                    else:
                        loader = False
                if not loader:
                    pending.wait()
                    continue    # re-check the cache (load may have failed)
                try:
                    model = self._fn(instance, model_id)
                except BaseException:
                    with st["lock"]:
                        st["loading"].pop(model_id, None)
                    pending.set()
                    raise
                with st["lock"]:
                    models = st["models"]
                    models[model_id] = model
                    models.move_to_end(model_id)
                    while len(models) > self._max:
                        models.popitem(last=False)   # LRU evict; GC frees
                    st["loading"].pop(model_id, None)
                pending.set()
                return model

        bound.__name__ = getattr(self._fn, "__name__", "multiplexed")
        return bound


def multiplexed(_func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """`@serve.multiplexed` on a loader method `def load(self, model_id)`:
    calls hit an LRU; at most max_num_models_per_replica stay resident."""

    def wrap(fn: Callable) -> _MultiplexWrapper:
        return _MultiplexWrapper(fn, max_num_models_per_replica)

    return wrap(_func) if _func is not None else wrap


def rendezvous_pick(replica_keys, model_id: str):
    """Stable replica choice for a model id (highest-random-weight hash):
    adding/removing a replica only remaps ~1/n of the models."""
    def score(rkey) -> int:
        h = hashlib.blake2b(f"{rkey}:{model_id}".encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "big")

    return max(replica_keys, key=score)
