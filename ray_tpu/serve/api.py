"""Public serve API: @serve.deployment, serve.run, handles, status.

Reference: `serve/api.py` (`serve.run :521`), `serve/deployment.py`
(@deployment decorator producing Deployment objects whose `.bind()` builds
an Application graph).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle

_PROXY_NAME = "SERVE_PROXY"


@dataclasses.dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: Any = 1          # int or "auto" (autoscaling defaults)
    num_cpus: float = 1
    num_tpus: float = 0
    route_prefix: Optional[str] = None
    # Per-replica concurrency (reference: max_ongoing_requests) — maps to
    # the replica actor's max_concurrency; also what @serve.batch needs to
    # see concurrent requests at all.
    max_ongoing_requests: int = 8
    # Keys (see serve/_private/autoscale.py AUTOSCALING_DEFAULTS):
    # {"min_replicas", "max_replicas", "target_ongoing_requests",
    #  "upscale_delay_s", "downscale_delay_s", "queue_wait_target_s",
    #  "slot_utilization_target"}
    autoscaling_config: Optional[Dict[str, Any]] = None
    # Generator deployments: HTTP responses stream chunk-by-chunk and
    # handles default to DeploymentResponseGenerator (reference:
    # StreamingResponse over uvicorn).
    stream: bool = False

    def options(self, **overrides) -> "Deployment":
        return dataclasses.replace(self, **overrides)

    def bind(self, *init_args, **init_kwargs) -> "Application":
        return Application(self, init_args, init_kwargs)


class Application:
    """A bound deployment graph node (reference: `serve/_private/build_app`).

    Binding another Application as an init arg expresses composition: the
    inner deployment is deployed too and the outer replica receives a
    DeploymentHandle in its place.
    """

    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs

    def _collect(self, app_name: str, out: List[Dict[str, Any]],
                 is_ingress: bool) -> DeploymentHandle:
        args = tuple(
            a._collect(app_name, out, False) if isinstance(a, Application)
            else a for a in self.init_args)
        kwargs = {
            k: (v._collect(app_name, out, False)
                if isinstance(v, Application) else v)
            for k, v in self.init_kwargs.items()
        }
        d = self.deployment
        from ray_tpu.serve._private.autoscale import (
            AUTOSCALING_DEFAULTS, validate_autoscaling_config)

        autoscaling = d.autoscaling_config
        num_replicas = d.num_replicas
        if num_replicas == "auto":
            # "auto" routes through the controller's AutoscalePolicy:
            # the deployment *starts* at min_replicas but scales between
            # min/max on the metrics plane (it used to pin to min and
            # never move when no autoscaling_config was given).
            autoscaling = dict(autoscaling or {})
            autoscaling.setdefault("mode", "metrics")
        if autoscaling is not None:
            autoscaling = {**AUTOSCALING_DEFAULTS, **autoscaling}
            validate_autoscaling_config(autoscaling)
        if num_replicas == "auto":
            num_replicas = autoscaling["min_replicas"]
        if not any(spec["name"] == d.name for spec in out):
            out.append({
                "name": d.name,
                "serialized_callable": cloudpickle.dumps(d.func_or_class),
                "init_args": args,
                "init_kwargs": kwargs,
                "num_replicas": num_replicas,
                "num_cpus": d.num_cpus,
                "num_tpus": d.num_tpus,
                "route_prefix": d.route_prefix,
                "is_ingress": is_ingress,
                "max_ongoing_requests": d.max_ongoing_requests,
                "autoscaling_config": autoscaling,
                "stream": d.stream,
                # @serve.ingress(app)-wrapped classes: the proxy forwards
                # the raw HTTP request and writes back status/headers/body.
                "asgi": bool(getattr(d.func_or_class, "_serve_is_asgi",
                                     False)),
            })
        return DeploymentHandle(app_name, d.name)


def deployment(func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Any = 1, num_cpus: float = 1,
               num_tpus: float = 0, route_prefix: Optional[str] = None,
               max_ongoing_requests: int = 8,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               stream: bool = False):
    def wrap(target):
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas, num_cpus=num_cpus,
            num_tpus=num_tpus, route_prefix=route_prefix,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config, stream=stream)

    return wrap(func_or_class) if func_or_class is not None else wrap


# ---------------------------------------------------------------- lifecycle

def start(http_port: int = 0, proxy_location: str = "HeadOnly",
          http_host: Optional[str] = None):
    """Start the HTTP ingress (controller starts lazily on first run()).

    ``proxy_location="EveryNode"`` pins one proxy actor per alive node
    (reference: ProxyLocation.EveryNode — each node accepts traffic and
    routes to replicas anywhere), returning the head-node proxy.

    The proxy binds loopback by default (it has no authentication);
    EveryNode implies 0.0.0.0 because cross-node ingress is the point,
    and ``http_host`` overrides either way.
    """
    from ray_tpu.serve._private.controller import get_or_create_controller

    get_or_create_controller()
    from ray_tpu.serve._private.proxy import ProxyActor

    if proxy_location == "EveryNode":
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        head = None
        for node in ray_tpu.nodes():
            if not node.get("Alive"):
                continue
            node_id = node["NodeID"]
            name = f"{_PROXY_NAME}:{node_id[:12]}"
            try:
                proxy = ray_tpu.get_actor(name)
            except Exception:
                proxy = ProxyActor.options(
                    name=name, lifetime="detached",
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=node_id, soft=False),
                ).remote(http_port, http_host or "0.0.0.0")
            if head is None:
                head = proxy
        return head
    try:
        return ray_tpu.get_actor(_PROXY_NAME)
    except Exception:
        return ProxyActor.options(
            name=_PROXY_NAME, lifetime="detached",
        ).remote(http_port, http_host or "127.0.0.1")


_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def start_grpc(grpc_port: int = 0, host: str = "127.0.0.1",
               grpc_servicer_functions=None):
    """Start the gRPC ingress (reference: grpc_options on serve.start →
    the gRPC proxy in `_private/proxy.py`). Shares the HTTP proxy's
    routing plane; see `serve/_private/grpc_proxy.py` for the wire
    contract. `grpc_servicer_functions`: generated
    ``add_XServicer_to_server`` callables (or their dotted import paths —
    pass strings when the proxy actor may run in a process that must
    re-import them) whose rpc methods the proxy serves with the user's
    own proto (de)serializers."""
    from ray_tpu.serve._private.controller import get_or_create_controller
    from ray_tpu.serve._private.grpc_proxy import (
        GrpcProxyActor, harvest_servicer_methods)

    get_or_create_controller()
    try:
        proxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
    except Exception:
        proxy = None
    if proxy is not None:
        if grpc_servicer_functions:
            # A live proxy without the requested servicers would answer
            # every user-proto rpc UNIMPLEMENTED with no hint why —
            # recreate it (the proxy is stateless) instead of silently
            # dropping the argument.
            wanted = set(harvest_servicer_methods(grpc_servicer_functions))
            have = set(ray_tpu.get(
                proxy.get_user_method_paths.remote(), timeout=30))
            if not wanted <= have:
                import time as _time

                ray_tpu.kill(proxy)
                deadline = _time.monotonic() + 10
                while _time.monotonic() < deadline:
                    try:
                        ray_tpu.get_actor(_GRPC_PROXY_NAME)
                        _time.sleep(0.1)   # name not released yet
                    except Exception:
                        break
                proxy = None
        if proxy is not None:
            return proxy
    return GrpcProxyActor.options(
        name=_GRPC_PROXY_NAME, lifetime="detached",
    ).remote(grpc_port, host,
             servicer_functions=grpc_servicer_functions)


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None,
        _overrides: Optional[Dict[str, Dict[str, Any]]] = None
        ) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its ingress handle.

    `_overrides` maps deployment name -> spec overrides; it is how
    declarative config deploys (`serve/schema.py`) re-tune a code-defined
    app without editing code (reference: config fields shadow @deployment
    options)."""
    from ray_tpu.serve._private.controller import get_or_create_controller

    controller = get_or_create_controller()
    specs: List[Dict[str, Any]] = []
    handle = app._collect(name, specs, True)
    if route_prefix is not None:
        for spec in specs:
            if spec["is_ingress"]:
                spec["route_prefix"] = route_prefix
    if _overrides:
        unknown = set(_overrides) - {s["name"] for s in specs}
        if unknown:
            raise ValueError(
                f"config overrides reference deployment(s) "
                f"{sorted(unknown)} not present in app {name!r} "
                f"(has {sorted(s['name'] for s in specs)})")
        for spec in specs:
            ov = dict(_overrides.get(spec["name"], ()))
            if not ov:
                continue
            wants_auto = ov.get("num_replicas") == "auto"
            if wants_auto:
                ov.pop("num_replicas")
            if wants_auto or "autoscaling_config" in ov:
                # Same defaults merge _collect applies to code-defined
                # configs — a partial config dict must never reach the
                # controller (reconcile KeyErrors on missing knobs).
                from ray_tpu.serve._private.autoscale import (
                    AUTOSCALING_DEFAULTS, validate_autoscaling_config)

                auto = {
                    **AUTOSCALING_DEFAULTS,
                    **(spec.get("autoscaling_config") or {}),
                    **(ov.get("autoscaling_config") or {}),
                }
                if wants_auto:
                    auto.setdefault("mode", "metrics")
                validate_autoscaling_config(auto)
                ov["autoscaling_config"] = auto
                if wants_auto:
                    ov["num_replicas"] = auto["min_replicas"]
            spec.update(ov)
    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    return handle


def get_app_handle(name: str = "default") -> DeploymentHandle:
    from ray_tpu.serve._private.controller import get_or_create_controller

    controller = get_or_create_controller()
    ingress = ray_tpu.get(controller.get_ingress.remote(name), timeout=30)
    if ingress is None:
        raise KeyError(f"no application '{name}'")
    return DeploymentHandle(name, ingress)


def status(name: str = "default") -> List[Dict[str, Any]]:
    from ray_tpu.serve._private.controller import get_or_create_controller

    return ray_tpu.get(
        get_or_create_controller().list_deployments.remote(name), timeout=30)


def list_applications() -> List[str]:
    """Names of deployed applications; [] when serve was never started
    (read-only: does NOT spawn a controller)."""
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return []
    return ray_tpu.get(controller.list_applications.remote(), timeout=30)


def delete(name: str) -> None:
    from ray_tpu.serve._private.controller import get_or_create_controller

    ray_tpu.get(get_or_create_controller().delete_application.remote(name),
                timeout=60)


def shutdown(graceful_timeout_s: float = 20.0) -> None:
    """Tear serve down, bounded end to end.

    Graceful first (controller drains replicas), then ``ray_tpu.kill``,
    then — if a serve system actor's worker process is STILL alive past
    the deadline — SIGKILL it directly. A wedged controller/proxy must
    never hang the caller: one stuck teardown used to cascade into
    setup timeouts for every test that followed (reference discipline:
    `serve/_private/controller.py` graceful_shutdown + fixture kills in
    `python/ray/tests/conftest.py`)."""
    import os
    import signal
    import time as _time

    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    names = (_PROXY_NAME, _GRPC_PROXY_NAME, CONTROLLER_NAME)
    deadline = _time.monotonic() + graceful_timeout_s

    # Snapshot the system actors' worker pids BEFORE killing, for the
    # hard backstop below.
    pids = []
    try:
        from ray_tpu.util import state as _state

        workers = {w["worker_id"]: w.get("pid")
                   for w in _state.list_workers()}
        for a in _state.list_actors():
            if a.get("name") in names and a.get("state") != "DEAD":
                pid = workers.get(a.get("worker_id"))
                if pid:
                    pids.append(int(pid))
    except Exception:
        pass

    for actor_name in names:
        try:
            actor = ray_tpu.get_actor(actor_name)
        except Exception:
            continue
        if actor_name == CONTROLLER_NAME:
            try:
                ray_tpu.get(actor.graceful_shutdown.remote(),
                            timeout=max(2.0, deadline - _time.monotonic()))
            except Exception:
                pass
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    # Hard backstop: wait briefly for the processes to die, then SIGKILL
    # survivors. os.kill only reaches same-host pids, which is exactly
    # the wedge this guards (test clusters are single-host; multi-node
    # kills already went through the raylet above).
    kill_deadline = _time.monotonic() + 5.0
    for pid in pids:
        while _time.monotonic() < kill_deadline:
            try:
                os.kill(pid, 0)
            except OSError:
                break  # gone
            _time.sleep(0.1)
        else:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
