"""Declarative config-file deploys (reference: `serve/schema.py:519,735`
pydantic schemas + `serve deploy` in `serve/scripts.py`).

A config is a dict (usually loaded from YAML)::

    applications:
      - name: default
        import_path: my_module:app       # module path to a bound Application
        route_prefix: /api
        args: {...}                      # optional builder kwargs
        deployments:                     # optional per-deployment overrides
          - name: Model
            num_replicas: 4
            max_ongoing_requests: 16
            autoscaling_config: {min_replicas: 1, max_replicas: 8}

``import_path`` targets either a bound ``Application`` or a callable
``(**args) -> Application`` (the reference's app-builder pattern).
Validation is plain-dataclass (no pydantic in this environment) but
rejects the same classes of errors: unknown fields, missing import_path,
duplicate app names / route prefixes, malformed overrides.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

_DEPLOYMENT_OVERRIDE_FIELDS = {
    "name", "num_replicas", "num_cpus", "num_tpus", "max_ongoing_requests",
    "autoscaling_config", "route_prefix",
}
_APP_FIELDS = {"name", "import_path", "route_prefix", "args", "deployments"}


class SchemaError(ValueError):
    pass


@dataclasses.dataclass
class DeploymentOverride:
    name: str
    overrides: Dict[str, Any]

    @classmethod
    def parse(cls, raw: Dict[str, Any], app: str) -> "DeploymentOverride":
        if not isinstance(raw, dict) or "name" not in raw:
            raise SchemaError(
                f"app {app!r}: each deployments entry needs a 'name'")
        unknown = set(raw) - _DEPLOYMENT_OVERRIDE_FIELDS
        if unknown:
            raise SchemaError(
                f"app {app!r} deployment {raw['name']!r}: unknown "
                f"field(s) {sorted(unknown)}")
        ov = {k: v for k, v in raw.items() if k != "name"}
        if "num_replicas" in ov and ov["num_replicas"] != "auto" and (
                not isinstance(ov["num_replicas"], int)
                or ov["num_replicas"] < 0):
            raise SchemaError(
                f"app {app!r} deployment {raw['name']!r}: num_replicas "
                f"must be a non-negative int or 'auto'")
        if "autoscaling_config" in ov:
            if not isinstance(ov["autoscaling_config"], dict):
                raise SchemaError(
                    f"app {app!r} deployment {raw['name']!r}: "
                    f"autoscaling_config must be a mapping")
            from ray_tpu.serve._private.autoscale import (
                validate_autoscaling_config)

            # Reject impossible bounds HERE, with the app/deployment in
            # the message — not at reconcile time deep in the controller.
            try:
                validate_autoscaling_config(ov["autoscaling_config"],
                                            error_cls=SchemaError)
            except SchemaError as e:
                raise SchemaError(
                    f"app {app!r} deployment {raw['name']!r}: {e}") \
                    from None
        return cls(name=raw["name"], overrides=ov)


@dataclasses.dataclass
class ApplicationSchema:
    name: str
    import_path: str
    route_prefix: Optional[str] = None
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentOverride] = dataclasses.field(
        default_factory=list)

    @classmethod
    def parse(cls, raw: Dict[str, Any], index: int) -> "ApplicationSchema":
        if not isinstance(raw, dict):
            raise SchemaError(f"applications[{index}] must be a mapping")
        name = raw.get("name", "default" if index == 0 else None)
        if not name:
            raise SchemaError(f"applications[{index}]: 'name' is required")
        unknown = set(raw) - _APP_FIELDS
        if unknown:
            raise SchemaError(
                f"app {name!r}: unknown field(s) {sorted(unknown)}")
        if not raw.get("import_path") or ":" not in raw["import_path"]:
            raise SchemaError(
                f"app {name!r}: 'import_path' must look like "
                f"'module.sub:attr'")
        args = raw.get("args") or {}
        if not isinstance(args, dict):
            raise SchemaError(f"app {name!r}: 'args' must be a mapping")
        return cls(
            name=name, import_path=raw["import_path"],
            route_prefix=raw.get("route_prefix"), args=args,
            deployments=[DeploymentOverride.parse(d, name)
                         for d in raw.get("deployments", [])])


@dataclasses.dataclass
class DeploySchema:
    applications: List[ApplicationSchema]

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "DeploySchema":
        if not isinstance(raw, dict) or "applications" not in raw:
            raise SchemaError("config must be a mapping with 'applications'")
        apps = [ApplicationSchema.parse(a, i)
                for i, a in enumerate(raw["applications"])]
        names = [a.name for a in apps]
        if len(set(names)) != len(names):
            raise SchemaError(f"duplicate application names in {names}")
        prefixes = [a.route_prefix for a in apps if a.route_prefix]
        if len(set(prefixes)) != len(prefixes):
            raise SchemaError(f"duplicate route_prefix in {prefixes}")
        return cls(applications=apps)


# ------------------------------------------------------------------ deploy

def import_application(import_path: str, args: Optional[Dict] = None):
    """'module.sub:attr' -> bound Application (calling attr(**args) if it
    is an app-builder callable rather than a pre-bound Application)."""
    from ray_tpu.serve.api import Application

    mod_name, _, attr = import_path.partition(":")
    target = importlib.import_module(mod_name)
    for part in attr.split("."):
        target = getattr(target, part)
    if isinstance(target, Application):
        if args:
            raise SchemaError(
                f"{import_path} is a bound Application; 'args' only apply "
                f"to app-builder functions")
        return target
    app = target(**(args or {}))
    if not isinstance(app, Application):
        raise SchemaError(
            f"{import_path} returned {type(app).__name__}, expected a "
            f"bound Application")
    return app


def deploy_config(config: Dict[str, Any]) -> List[str]:
    """Validate + deploy every application in the config. Returns the
    deployed app names. Apps present in a previous deploy but absent from
    this config are left running (reference `serve deploy` replaces the
    full target state; use serve.delete for removal — kept explicit
    here)."""
    from ray_tpu.serve import api

    schema = DeploySchema.parse(config)
    deployed = []
    for app in schema.applications:
        bound = import_application(app.import_path, app.args)
        overrides = {d.name: d.overrides for d in app.deployments}
        api.run(bound, name=app.name, route_prefix=app.route_prefix,
                _overrides=overrides)
        deployed.append(app.name)
    return deployed


def deploy_config_file(path: str) -> List[str]:
    import yaml

    with open(path) as f:
        return deploy_config(yaml.safe_load(f))
